"""Serving engine: KV-cached autoregressive decoding for GPT-family models.

Framework infrastructure, not a "model": the decode core (prefill +
single-token cached step), weight-only int8 quantization, int8 KV caches,
greedy/sampled and beam decoding loops, and the decode-param memo all live
here; `models/transformer.py` keeps only the model definitions and thin
`generate()`/`generate_beam()` wrappers.

The reference's LLM-serving story is ONNX-imported GPT-2 replaying the
full graph per token (/root/reference/examples/onnx/gpt2/gpt2.py re-runs
the whole prefix each step). TPU-native redesign: one jitted function =
prefill + lax.scan over decode steps with a preallocated (T-length) KV
cache updated via dynamic_update_slice — O(T) per token instead of
O(T^2), no retrace per step, static shapes throughout.

Serving-roofline design notes (PROFILE.md "KV-cached decode"):
- HEAD-PACKED KV caches, (B, H/P, T, P*D) with P = 128//D: TPU bf16
  tiles are (16 sublanes, 128 lanes), so a (B,H,T,D) cache with D=64
  pads every row to 128 lanes — the cache physically occupies and
  STREAMS 2x its logical bytes. Packing P heads into the minor dim
  fills the lanes while keeping the per-token cache update a contiguous
  row write; scores stay exactly per-head via BLOCK-DIAGONAL queries.
- Wq/Wk/Wv fuse into one (E, 3E) matmul at decode-param prep.
- `dtype="int8"` weight-only quantization (per-output-channel symmetric)
  halves the dominant weight traffic; `kv_dtype="int8"` additionally
  quantizes the KV cache with per-(head, position) scales.
"""

from __future__ import annotations

import weakref

#: every KV-cache storage mode the serving stack supports (the
#: `kv_dtype=` label on singa_serve_* metrics is proven against this
#: tuple by tools/check_metrics_names.py rule 5). "fp" is the
#: activation-dtype cache (the kv_dtype=None API spelling), int8 the
#: per-(head, position)-scaled byte cache, int4 the packed-nibble cache
#: (two values per byte, same scale layout, bytes halved again).
KV_DTYPES = ("fp", "int8", "int4")

#: speculative-decoding per-token verdicts (the `verdict=` label on
#: singa_spec_tokens_total is proven against this tuple by rule 5):
#: "drafted" counts every draft proposal, "accepted" the proposals the
#: target verified, "bonus" the target's own token each verify round
#: emits for free, "wasted" = drafted - accepted (rejected proposals —
#: the compute spent buying nothing).
SPEC_VERDICTS = ("drafted", "accepted", "bonus", "wasted")

#: quantized-KV modes (subset of KV_DTYPES the quantizer handles)
_KVQ = ("int8", "int4")


def kv_label(kv_dtype) -> str:
    """Map the API spelling (None/'int8'/'int4') onto KV_DTYPES."""
    label = kv_dtype or "fp"
    assert label in KV_DTYPES, kv_dtype
    return label


def _quant8(W):
    """Per-output-channel symmetric int8 quantization of a (in, out)
    weight: q8 int8 + fp32 scale row. The scale commutes with the
    contraction (y_j = (sum_i x_i q_ij) * s_j), so the matmul runs on the
    int8 bytes and only the tiny (out,) output is rescaled — halving
    weight HBM traffic vs bf16 on the bandwidth-bound decode path."""
    import jax.numpy as jnp
    s = jnp.max(jnp.abs(W), axis=0, keepdims=True) / 127.0
    s = jnp.maximum(s, 1e-8)
    q = jnp.clip(jnp.round(W / s), -127, 127).astype(jnp.int8)
    return {"q8": q, "sc": s.astype(jnp.float32)}


def _mm(x, W):
    """x @ W where W is a plain array or a _quant8 dict."""
    if isinstance(W, dict):
        y = x @ W["q8"].astype(x.dtype)
        return y * W["sc"].astype(x.dtype)
    return x @ W


_Q8_KEYS = ("Wqkv", "Wo", "W1", "W2", "head")


def _cast_params(p, dtype):
    """Decode-param tree in the serving dtype: None = as-stored (fp32),
    "bfloat16" = bf16 weights/activations, "int8" = weight-only int8
    (the big streamed matrices quantize; biases, LN params, embedding —
    its gather reads only B rows — and MoE weights stay bf16; W8A16)."""
    import jax
    import jax.numpy as jnp
    if dtype is None:
        return p
    if dtype != "int8":
        cd = jnp.dtype(dtype)
        return jax.tree.map(
            lambda a: a.astype(cd)
            if jnp.issubdtype(a.dtype, jnp.floating) else a, p)
    bf = jnp.bfloat16

    def cast_leaf(a):
        return a.astype(bf) \
            if jnp.issubdtype(a.dtype, jnp.floating) else a

    out = {k: cast_leaf(v) for k, v in p.items() if k != "blocks"}
    out["head"] = _quant8(p["head"])
    blocks = []
    for bp in p["blocks"]:
        nb = {k: cast_leaf(v) for k, v in bp.items()}
        for k in _Q8_KEYS:
            if k in bp:
                nb[k] = _quant8(bp[k])
        blocks.append(nb)
    out["blocks"] = blocks
    return out


class _DecodeCore:
    """Shared functional decode math for greedy/sampled and beam decoding.

    One implementation of the fp32-island LayerNorm, the causal prefill
    (which also fills the KV caches), and the single-token cached block
    step — so every decode flavor shares numerics by construction (the
    beam-1 == greedy test leans on this). See the module docstring for
    the roofline design notes.
    """

    def __init__(self, H, E, S0, T, scale, moe_ks=None, kv_heads=None,
                 rope=False, rope_theta=10000.0, kv_dtype=None):
        self.H, self.E, self.S0, self.T, self.scale = H, E, S0, T, scale
        self.rope = bool(rope)
        self.rope_theta = float(rope_theta)
        # quantized KV (kv_dtype "int8" or "int4"): per-(head, position)
        # symmetric scales. The algebra stays exact-in-structure:
        # K-scales multiply scores per source position after the packed
        # matmul, and V-scales fold into the attention weights for the
        # DIAGONAL (own-head) block — the only block the packed
        # extraction keeps, so the off-block garbage scaling is
        # discarded with the cross-terms. int4 packs two nibbles per
        # byte along the lane dim (ops.attention.nibble_pack's
        # split-half layout) with the same scale shapes; only the
        # quantization basis (max|kv|/7) and the byte stream change.
        assert kv_dtype in (None,) + _KVQ, kv_dtype
        self.kv_dtype = kv_dtype
        self.kv8 = kv_dtype == "int8"
        self.kv4 = kv_dtype == "int4"
        self.kvq = kv_dtype in _KVQ
        # static per-layer MoE routing degree (None = dense MLP); must be
        # static (int() under jit) so it lives here, not in the param tree
        self.moe_ks = moe_ks or []
        # GQA: Hkv kv heads each serve G = H/Hkv query heads; the caches
        # hold Hkv heads (the serving win — KV traffic shrinks G x) and
        # the packed block-diagonal contraction places G query rows per
        # kv-head block instead of 1
        self.Hkv = kv_heads or H
        self.G = H // self.Hkv
        D = E // H
        P = max(1, 128 // D)
        self.P = P if (P > 1 and self.Hkv % P == 0) else 1

    def cast(self, p, dtype):
        return _cast_params(p, dtype)

    def ln(self, x, g, b, eps=1e-5):
        # fp32 island like autograd.LayerNorm: variance in bf16 is
        # catastrophically lossy
        import jax.numpy as jnp
        from jax import lax
        x32 = x.astype(jnp.float32)
        m = jnp.mean(x32, axis=-1, keepdims=True)
        v = jnp.var(x32, axis=-1, keepdims=True)
        y = (x32 - m) * lax.rsqrt(v + eps) * g.astype(jnp.float32) \
            + b.astype(jnp.float32)
        return y.astype(x.dtype)

    def mlp(self, bp, x, li):
        """Block MLP on (..., E): dense two-layer, or the MoE FFN when
        layer `li` routes to experts (decode uses the single-device
        dense-dispatch path; generous capacity so no token drops)."""
        import jax
        import jax.numpy as jnp
        kcf = self.moe_ks[li] if li < len(self.moe_ks) else None
        if kcf is not None:
            # NOTE: capacity-limited routing is a BATCH-GLOBAL effect (a
            # token's drop depends on the other tokens in the dispatch),
            # so cached decode == full forward only in the no-drop regime
            # (generous capacity_factor); the layer's own factor is used
            # here for honest replication.
            k, cf = kcf
            from .parallel.moe import moe_ffn
            lead = x.shape[:-1]
            flat = x.reshape(-1, x.shape[-1])
            y, _, _ = moe_ffn(flat, bp["moeWg"], bp["moeW1"], bp["moeb1"],
                              bp["moeW2"], bp["moeb2"],
                              capacity_factor=cf, k=k)
            return y.reshape(*lead, x.shape[-1]).astype(x.dtype)
        return _mm(jax.nn.gelu(_mm(x, bp["W1"]) + bp["bb1"]),
                   bp["W2"]) + bp["bb2"]

    def qkv(self, bp, x, n, S=None):
        """Fused QKV projection: one (E, E + 2*Hkv*D) matmul, split into
        q (n,[S,]H,D) and k/v (n,[S,]Hkv,D)."""
        import jax.numpy as jnp
        H, D, E, Hkv = self.H, self.E // self.H, self.E, self.Hkv
        KE = Hkv * D
        fused = _mm(x, bp["Wqkv"]) + bp["bqkv"]
        bounds = ((0, E, H), (E, E + KE, Hkv), (E + KE, E + 2 * KE, Hkv))
        if S is None:
            q, k, v = (fused[..., a:b].reshape(n, h, D)
                       for a, b, h in bounds)
        else:
            q, k, v = (fused[..., a:b].reshape(n, S, h, D).swapaxes(1, 2)
                       for a, b, h in bounds)
        return q, k, v

    def _pack(self, kv, n, S):
        """(n,Hkv,S,D) per-kv-head K/V -> head-packed
        (n, Hkv/P, S, P*D)."""
        D, P, Hkv = self.E // self.H, self.P, self.Hkv
        return kv.reshape(n, Hkv // P, P, S, D).swapaxes(2, 3) \
            .reshape(n, Hkv // P, S, P * D)

    def _quant_kv(self, kv, n, S):
        """(n,Hkv,S,D) -> (packed quantized cache rows, scales
        (n,Hp,S,P) fp32): per-(head, position) symmetric. int8 mode
        yields int8 (n,Hp,S,P*D); int4 yields packed-nibble uint8
        (n,Hp,S,P*D/2) (two values per byte, split-half lane layout —
        see ops.attention.nibble_pack) on a max|kv|/7 basis."""
        import jax.numpy as jnp
        P, Hkv = self.P, self.Hkv
        qmax = 7.0 if self.kv4 else 127.0
        s = jnp.maximum(jnp.max(jnp.abs(kv.astype(jnp.float32)), axis=-1),
                        1e-8) / qmax                        # (n,Hkv,S)
        q = jnp.clip(jnp.round(kv.astype(jnp.float32) / s[..., None]),
                     -qmax, qmax).astype(jnp.int8)
        sp = s.reshape(n, Hkv // P, P, S).swapaxes(2, 3)    # (n,Hp,S,P)
        packed = self._pack(q, n, S)
        if self.kv4:
            from .ops.attention import nibble_pack
            packed = nibble_pack(packed)
        return packed, sp

    def _dequant_cache(self, packed, dtype):
        """Quantized cache rows -> matmul operand in `dtype` (int8 cast,
        int4 nibble unpack) for the XLA einsum paths; the Pallas
        kernels do the same transform in-kernel instead."""
        if self.kv4:
            from .ops.attention import nibble_unpack
            return nibble_unpack(packed, dtype)
        return packed.astype(dtype)

    def _scale_rows(self, sp, G):
        """(n,Hp,T,P) per-position scales -> (n,Hp,P*G,T) row factors
        (packed query row q = c*G + g reads lane block c)."""
        import jax.numpy as jnp
        return jnp.repeat(sp.swapaxes(2, 3), G, axis=2)

    def prefill_parts(self, p, prompt, n):
        """Causal pass over the (n, S) prompt (S from the prompt shape,
        so the serving engine's padded-bucket prompts reuse it): returns
        the final hidden states h (n, S, E) and the per-block RAW
        (rotated, unpacked) k/v (n, Hkv, S, D) — the shared front half
        of both the dense `prefill` (which pads them into T-length
        caches) and the engine's paged prefill (which scatters them into
        pool pages).

        Attention runs through the Pallas flash kernel (O(S) score
        memory — the same kernel the training path uses, GQA via repeat),
        so a 16k+-token prompt prefills on one chip instead of
        materializing an (S, S) score matrix per head; short prompts
        that don't tile the kernel fall back to the O(S^2) reference
        path inside flash_attention itself."""
        import jax.numpy as jnp
        from .ops.attention import flash_attention
        D = self.E // self.H
        S = prompt.shape[1]
        ln = self.ln
        h = p["emb"][prompt] + (0 if self.rope else p["pos"][:S])

        kvs = []
        G = self.G
        if self.rope:
            from .autograd import rope_tables, apply_rope
            rcos, rsin = rope_tables(jnp.arange(S), D, self.rope_theta)
        for li, bp in enumerate(p["blocks"]):
            x = ln(h, bp["g1"], bp["b1"])
            q, k, v = self.qkv(bp, x, n, S)     # q (n,H,·); kv (n,Hkv,·)
            if self.rope:
                # rotate q/k; the cache stores ROTATED keys (standard),
                # so decode steps only rotate their own position
                q = apply_rope(q, rcos, rsin)
                k = apply_rope(k, rcos, rsin)
            kr = jnp.repeat(k, G, axis=1) if G > 1 else k
            vr = jnp.repeat(v, G, axis=1) if G > 1 else v
            o = flash_attention(q, kr, vr, True, self.scale)
            h = h + _mm(o.swapaxes(1, 2).reshape(n, S, self.E),
                        bp["Wo"]) + bp["bo"]
            x = ln(h, bp["g2"], bp["b2"])
            h = h + self.mlp(bp, x, li)
            kvs.append((k, v))
        return h, kvs

    def prefill(self, p, prompt, n):
        """Causal pass over the (n, S0) prompt; returns the last-position
        logits (n, V) and per-block head-packed KV caches of time-length
        T, shape (n, H/P, T, P*D) (see class docstring)."""
        import jax.numpy as jnp
        D, S0, T, P = self.E // self.H, self.S0, self.T, self.P
        Hkv = self.Hkv
        h, kvs = self.prefill_parts(p, prompt, n)
        caches = []
        qw = (P * D) // 2 if self.kv4 else P * D
        qd = jnp.uint8 if self.kv4 else jnp.int8
        for k, v in kvs:
            if self.kvq:
                k8, ks = self._quant_kv(k, n, S0)
                v8, vs = self._quant_kv(v, n, S0)
                Kc = (jnp.zeros((n, Hkv // P, T, qw), qd)
                      .at[:, :, :S0].set(k8),
                      jnp.zeros((n, Hkv // P, T, P), jnp.float32)
                      .at[:, :, :S0].set(ks))
                Vc = (jnp.zeros((n, Hkv // P, T, qw), qd)
                      .at[:, :, :S0].set(v8),
                      jnp.zeros((n, Hkv // P, T, P), jnp.float32)
                      .at[:, :, :S0].set(vs))
            else:
                Kc = jnp.zeros((n, Hkv // P, T, P * D), k.dtype) \
                    .at[:, :, :S0].set(self._pack(k, n, S0))
                Vc = jnp.zeros((n, Hkv // P, T, P * D), v.dtype) \
                    .at[:, :, :S0].set(self._pack(v, n, S0))
            caches.append((Kc, Vc))
        logits0 = _mm(self.ln(h[:, -1], p["gf"], p["bf"]), p["head"])
        return logits0, caches

    def _pack_q(self, q, n):
        """(n, H, D) per-head queries -> packed BLOCK-DIAGONAL
        (n, Hp, P*G, P*D): packed slot c holds kv head (hp*P + c)'s G
        query rows in block c, zeros elsewhere — the full-width
        contraction with the packed K then yields exactly the per-head
        scores (GQA: G rows per block; MHA is the G=1 case)."""
        import jax.numpy as jnp
        D, P, G = self.E // self.H, self.P, self.G
        Hp = self.Hkv // P
        ar = jnp.arange(P)
        q6 = jnp.moveaxis(q.reshape(n, Hp, P, G, D), 2, 0)
        return jnp.zeros((n, Hp, P, G, P, D), q.dtype) \
            .at[:, :, ar, :, ar, :].set(q6) \
            .reshape(n, Hp, P * G, P * D)

    def _unpack_o(self, O2, n):
        """(n, Hp, P*G, P*D) packed attention output -> (n, E): extract
        the DIAGONAL (own-head) blocks the packed contraction kept."""
        import jax.numpy as jnp
        D, P, G = self.E // self.H, self.P, self.G
        Hp = self.Hkv // P
        ar = jnp.arange(P)
        return jnp.moveaxis(
            O2.reshape(n, Hp, P, G, P, D)[:, :, ar, :, ar, :],
            0, 2).reshape(n, self.E)

    def paged_token_step(self, p, tok, pools, page_table, lens, active,
                         n, page_size, n_pages, use_kernel=None):
        """One ragged decode step against the PAGED KV cache (the
        serving engine's hot path): feed token `tok` (n,) for each slot
        at its own position `lens[i]`, write the new K/V row into the
        slot's current page (inactive slots scatter out-of-bounds and
        are DROPPED), and attend over each slot's pages via
        ops.attention.paged_attention with per-slot lengths. Returns
        (logits (n, V), new pools).

        `pools` is a list per block: (K, V) of (n_pages, Hp, page_size,
        P*D), or with kv8 ((K8, Ks), (V8, Vs)) carrying the fp32
        per-(head, position) scale pools. Numerics match `token_step`
        by construction: same qkv/rope/pack/extract helpers, same scale
        folding — the paged==dense greedy agreement test leans on
        this."""
        import jax.numpy as jnp
        from .ops.attention import paged_attention
        D, E, P = self.E // self.H, self.E, self.P
        G = self.G
        ln = self.ln
        ps = page_size
        # clamp positions so an inactive slot's stale length can never
        # index outside the table/pos-embedding (its output is masked)
        pos = jnp.minimum(lens, self.T - 1)
        h = p["emb"][tok] + (0 if self.rope else p["pos"][pos])
        if self.rope:
            from .autograd import rope_tables, apply_rope
            rcos, rsin = rope_tables(pos, D, self.rope_theta)  # (n, D)
            rcos, rsin = rcos[:, None, :], rsin[:, None, :]
        nidx = jnp.arange(n)
        # inactive slots write to page id n_pages: out of bounds, and
        # the scatter uses mode="drop" — no trash page needed
        pvec = jnp.where(active, page_table[nidx, pos // ps], n_pages)
        off = pos % ps
        ln_att = jnp.where(active, pos + 1, 1)
        new_pools = []
        for li, (bp, pool) in enumerate(zip(p["blocks"], pools)):
            x = ln(h, bp["g1"], bp["b1"])
            q, kn, vn = self.qkv(bp, x, n)   # q (n,H,D); kv (n,Hkv,D)
            if self.rope:
                q = apply_rope(q, rcos, rsin)
                kn = apply_rope(kn, rcos, rsin)
            if self.kvq:
                (K8, Ks), (V8, Vs) = pool
                k8, ks = self._quant_kv(kn[:, :, None], n, 1)
                v8, vs = self._quant_kv(vn[:, :, None], n, 1)
                K8 = K8.at[pvec, :, off, :].set(k8[:, :, 0], mode="drop")
                Ks = Ks.at[pvec, :, off, :].set(ks[:, :, 0], mode="drop")
                V8 = V8.at[pvec, :, off, :].set(v8[:, :, 0], mode="drop")
                Vs = Vs.at[pvec, :, off, :].set(vs[:, :, 0], mode="drop")
                pool = ((K8, Ks), (V8, Vs))
                Kmat, Vmat, Ksc, Vsc = K8, V8, Ks, Vs
            else:
                K, V = pool
                K = K.at[pvec, :, off, :].set(
                    self._pack(kn[:, :, None], n, 1)[:, :, 0],
                    mode="drop")
                V = V.at[pvec, :, off, :].set(
                    self._pack(vn[:, :, None], n, 1)[:, :, 0],
                    mode="drop")
                pool = (K, V)
                Kmat, Vmat, Ksc, Vsc = K, V, None, None
            Q2 = self._pack_q(q, n)
            O2 = paged_attention(
                Q2, Kmat, Vmat, page_table, ln_att, ps,
                scale=self.scale, k_scales=Ksc, v_scales=Vsc,
                groups=G, use_kernel=use_kernel)
            o = self._unpack_o(O2.astype(x.dtype), n)
            h = h + _mm(o, bp["Wo"]) + bp["bo"]
            x = ln(h, bp["g2"], bp["b2"])
            h = h + self.mlp(bp, x, li)
            new_pools.append(pool)
        logits = _mm(ln(h, p["gf"], p["bf"]), p["head"])
        return logits, new_pools

    def paged_verify_step(self, p, toks, pools, page_table, lens,
                          active, n, page_size, n_pages, k,
                          use_kernel=None, write_limits=None):
        """The speculative VERIFY step against the PAGED pool: feed
        `toks` (n, k) at per-slot positions lens[i]..lens[i]+k-1 in ONE
        batched forward — write all k K/V rows into each slot's pages
        (inactive slots, and positions at or past `write_limits`
        (exclusive bound, default the page-table horizon), scatter
        out-of-bounds and DROP), then attend via paged_attention's
        q_tokens causal ladder. Returns (logits (n, k, V), new pools):
        logits[:, j] equals the j-th sequential paged_token_step's
        logits for every committed position — the engine's spec==greedy
        anchor. Dropped-write positions only ever feed DISCARDED ladder
        outputs (take is capped at the slot's remaining budget)."""
        import jax.numpy as jnp
        from .ops.attention import paged_attention
        D, E, P = self.E // self.H, self.E, self.P
        G = self.G
        ln = self.ln
        ps = page_size
        nidx = jnp.arange(n)
        posk = lens[:, None] + jnp.arange(k)[None, :]      # (n, k)
        pos_emb = jnp.minimum(posk, self.T - 1)
        h = p["emb"][toks] + (0 if self.rope else p["pos"][pos_emb])
        if self.rope:
            from .autograd import rope_tables, apply_rope
            rcos, rsin = rope_tables(pos_emb.reshape(-1), D,
                                     self.rope_theta)
            rcos = rcos.reshape(n, k, D)[:, None]          # (n,1,k,D)
            rsin = rsin.reshape(n, k, D)[:, None]
        wl = write_limits if write_limits is not None \
            else jnp.full((n,), self.T, jnp.int32)
        ok_w = active[:, None] & (posk < wl[:, None])
        pvec = jnp.where(ok_w, page_table[nidx[:, None],
                                          posk // ps], n_pages)
        off = posk % ps
        ln_att = jnp.where(active, lens + k, 1)
        new_pools = []
        for li, (bp, pool) in enumerate(zip(p["blocks"], pools)):
            x = ln(h, bp["g1"], bp["b1"])
            q, kn, vn = self.qkv(bp, x, n, S=k)  # q (n,H,k,D)
            if self.rope:
                q = apply_rope(q, rcos, rsin)
                kn = apply_rope(kn, rcos, rsin)
            if self.kvq:
                (K8, Ks), (V8, Vs) = pool
                k8, ks = self._quant_kv(kn, n, k)
                v8, vs = self._quant_kv(vn, n, k)
                K8 = K8.at[pvec, :, off, :].set(
                    k8.swapaxes(1, 2), mode="drop")
                Ks = Ks.at[pvec, :, off, :].set(
                    ks.swapaxes(1, 2), mode="drop")
                V8 = V8.at[pvec, :, off, :].set(
                    v8.swapaxes(1, 2), mode="drop")
                Vs = Vs.at[pvec, :, off, :].set(
                    vs.swapaxes(1, 2), mode="drop")
                pool = ((K8, Ks), (V8, Vs))
                Kmat, Vmat, Ksc, Vsc = K8, V8, Ks, Vs
            else:
                K, V = pool
                kp = self._pack(kn, n, k)
                vp = self._pack(vn, n, k)
                K = K.at[pvec, :, off, :].set(
                    kp.swapaxes(1, 2), mode="drop")
                V = V.at[pvec, :, off, :].set(
                    vp.swapaxes(1, 2), mode="drop")
                pool = (K, V)
                Kmat, Vmat, Ksc, Vsc = K, V, None, None
            Q2 = self._pack_q_multi(q, n, k)
            O2 = paged_attention(
                Q2, Kmat, Vmat, page_table, ln_att, ps,
                scale=self.scale, k_scales=Ksc, v_scales=Vsc,
                groups=G, use_kernel=use_kernel, q_tokens=k)
            o = self._unpack_o_multi(O2.astype(x.dtype), n, k)
            h = h + _mm(o, bp["Wo"]) + bp["bo"]
            x = ln(h, bp["g2"], bp["b2"])
            h = h + self.mlp(bp, x, li)
            new_pools.append(pool)
        logits = _mm(ln(h, p["gf"], p["bf"]), p["head"])
        return logits, new_pools

    def token_step(self, p, tok, caches, i, n, use_kernel=None):
        """Feed token `tok` (n,) at generated-index `i` (position S0+i)
        through all blocks against the caches; returns (logits (n, V),
        new caches). `use_kernel=None` routes attention through the
        Pallas flash-decode kernel on TPU (in-kernel dequant for
        quantized caches) and the inline einsum math elsewhere."""
        import jax
        import jax.numpy as jnp
        from jax import lax
        H, D, E, P = self.H, self.E // self.H, self.E, self.P
        Hkv, G = self.Hkv, self.G
        Hp = Hkv // P
        ln = self.ln
        pos_idx = self.S0 + i
        h = p["emb"][tok] + (0 if self.rope else p["pos"][pos_idx])
        kmask = (jnp.arange(self.T) <= pos_idx)
        if self.rope:
            from .autograd import rope_tables, apply_rope
            rcos, rsin = rope_tables(pos_idx[None], D, self.rope_theta)
            rcos, rsin = rcos[0], rsin[0]          # (D,) broadcast
        new_caches = []
        for li, ((Kc, Vc), bp) in enumerate(zip(caches, p["blocks"])):
            x = ln(h, bp["g1"], bp["b1"])
            q, kn, vn = self.qkv(bp, x, n)   # q (n,H,D); kv (n,Hkv,D)
            if self.rope:
                q = apply_rope(q, rcos, rsin)
                kn = apply_rope(kn, rcos, rsin)
            # packed caches: one contiguous (P*D)-lane row per token
            if self.kvq:
                (K8, Ks), (V8, Vs) = Kc, Vc
                k8, ks = self._quant_kv(kn[:, :, None], n, 1)
                v8, vs = self._quant_kv(vn[:, :, None], n, 1)
                K8 = lax.dynamic_update_slice(K8, k8, (0, 0, pos_idx, 0))
                Ks = lax.dynamic_update_slice(Ks, ks, (0, 0, pos_idx, 0))
                V8 = lax.dynamic_update_slice(V8, v8, (0, 0, pos_idx, 0))
                Vs = lax.dynamic_update_slice(Vs, vs, (0, 0, pos_idx, 0))
                Kc, Vc = (K8, Ks), (V8, Vs)
                Kmat = self._dequant_cache(K8, x.dtype)
                Vmat = self._dequant_cache(V8, x.dtype)
            else:
                Kc = lax.dynamic_update_slice(
                    Kc, kn.reshape(n, Hp, 1, P * D), (0, 0, pos_idx, 0))
                Vc = lax.dynamic_update_slice(
                    Vc, vn.reshape(n, Hp, 1, P * D), (0, 0, pos_idx, 0))
                Kmat, Vmat = Kc, Vc
            # block-diagonal queries (see _pack_q): the full-width
            # contraction with the packed K yields exactly the per-head
            # scores (GQA: G rows per block; MHA is the G=1 case)
            Q2 = self._pack_q(q, n)
            use_k = use_kernel if use_kernel is not None \
                else jax.default_backend() == "tpu"
            if use_k:
                # TPU: the Pallas flash-decode kernel streams the cache
                # blockwise — quantized caches stream their BYTES and
                # dequantize in-kernel (the whole point of int8/int4);
                # the XLA einsum below would materialize the dequant
                from .ops.attention import flash_decode
                lens_att = jnp.broadcast_to(pos_idx + 1, (n,)) \
                    .astype(jnp.int32)
                if self.kvq:
                    O2 = flash_decode(
                        Q2, K8, V8, lens_att, scale=self.scale,
                        k_scales=Ks, v_scales=Vs, groups=G,
                        use_kernel=use_k).astype(x.dtype)
                else:
                    O2 = flash_decode(
                        Q2, Kc, Vc, lens_att, scale=self.scale,
                        groups=G, use_kernel=use_k).astype(x.dtype)
            else:
                s = jnp.einsum("nhqj,nhtj->nhqt", Q2, Kmat) * self.scale
                if self.kvq:
                    # K-scales: one factor per (source position, own
                    # block)
                    s = s * self._scale_rows(Ks, G)
                a = jax.nn.softmax(jnp.where(kmask, s, -jnp.inf),
                                   axis=-1)
                if self.kvq:
                    # V-scales fold into the weights for the own-head
                    # block (the only one extracted below)
                    a = (a * self._scale_rows(Vs, G)).astype(x.dtype)
                O2 = jnp.einsum("nhqt,nhtj->nhqj", a,
                                Vmat)           # (n,Hp,P*G,P*D)
            o = self._unpack_o(O2, n)
            h = h + _mm(o, bp["Wo"]) + bp["bo"]
            x = ln(h, bp["g2"], bp["b2"])
            h = h + self.mlp(bp, x, li)
            new_caches.append((Kc, Vc))
        logits = _mm(ln(h, p["gf"], p["bf"]), p["head"])
        return logits, new_caches

    def _pack_q_multi(self, q, n, k):
        """(n, H, k, D) per-head queries for k tokens -> packed
        block-diagonal (n, Hp, k*P*G, P*D), token-major rows (the
        (q_tokens, P, G) layout ops.attention's q_tokens ladder
        expects)."""
        import jax.numpy as jnp
        Hp = self.Hkv // self.P
        PG = self.P * self.G
        PD = self.P * (self.E // self.H)
        qf = q.swapaxes(1, 2).reshape(n * k, self.H,
                                      self.E // self.H)
        Q2 = self._pack_q(qf, n * k)            # (n*k, Hp, PG, PD)
        return jnp.moveaxis(Q2.reshape(n, k, Hp, PG, PD), 1, 2) \
            .reshape(n, Hp, k * PG, PD)

    def _unpack_o_multi(self, O2, n, k):
        """(n, Hp, k*P*G, P*D) packed attention output -> (n, k, E)."""
        import jax.numpy as jnp
        Hp = self.Hkv // self.P
        PG = self.P * self.G
        PD = self.P * (self.E // self.H)
        O5 = jnp.moveaxis(O2.reshape(n, Hp, k, PG, PD), 2, 1) \
            .reshape(n * k, Hp, PG, PD)
        return self._unpack_o(O5, n * k).reshape(n, k, self.E)

    def verify_step(self, p, toks, caches, pos, active, n, k,
                    use_kernel=None):
        """The speculative VERIFY step: feed `toks` (n, k) at per-row
        positions pos[i]..pos[i]+k-1 through all blocks in ONE batched
        forward — writes all k KV rows (per-row scatter; inactive rows
        and positions past the cache drop), then attends with the
        causal ladder (token j sees cache positions <= pos+j) via
        ops.attention.flash_decode's q_tokens mode. Returns (logits
        (n, k, V), new caches): logits[:, j] is the target's own next
        token after consuming toks[:, :j+1] — exactly the j-th
        sequential token_step's logits, which is what makes
        longest-accepted-prefix speculative decoding greedy-exact.
        k == 1 with a scalar-broadcast `pos` is token_step's math at
        per-row positions (the draft loop uses it that way)."""
        import jax
        import jax.numpy as jnp
        from .ops.attention import flash_decode
        D, E, P = self.E // self.H, self.E, self.P
        G, Hkv = self.G, self.Hkv
        Hp = Hkv // P
        ln = self.ln
        nidx = jnp.arange(n)
        posk = pos[:, None] + jnp.arange(k)[None, :]       # (n, k)
        pos_emb = jnp.minimum(posk, self.T - 1)
        h = p["emb"][toks] + (0 if self.rope
                              else p["pos"][pos_emb])      # (n, k, E)
        if self.rope:
            from .autograd import rope_tables, apply_rope
            rcos, rsin = rope_tables(pos_emb.reshape(-1), D,
                                     self.rope_theta)
            rcos = rcos.reshape(n, k, D)[:, None]          # (n,1,k,D)
            rsin = rsin.reshape(n, k, D)[:, None]
        # inactive rows and positions past the cache scatter to row T
        # and are DROPPED (the cache time dim is T)
        posw = jnp.where(active[:, None] & (posk < self.T), posk,
                         self.T)                           # (n, k)
        # NOT clamped to T: the ladder limit for token ti is
        # lens_att - (k-1-ti); clamping would truncate the LAST
        # tokens' masks in the final rounds near the cache end
        # (token ti must always see its own position pos+ti — the
        # positions past T it can also "see" were drop-written and
        # only ever feed discarded outputs)
        lens_att = pos + k                                 # (n,)
        new_caches = []
        for li, ((Kc, Vc), bp) in enumerate(zip(caches, p["blocks"])):
            x = ln(h, bp["g1"], bp["b1"])
            q, kn, vn = self.qkv(bp, x, n, S=k)  # q (n,H,k,D)
            if self.rope:
                q = apply_rope(q, rcos, rsin)
                kn = apply_rope(kn, rcos, rsin)
            if self.kvq:
                (K8, Ks), (V8, Vs) = Kc, Vc
                k8, ks = self._quant_kv(kn, n, k)   # (n,Hp,k,·)
                v8, vs = self._quant_kv(vn, n, k)
                K8 = K8.at[nidx[:, None], :, posw, :].set(
                    k8.swapaxes(1, 2), mode="drop")
                Ks = Ks.at[nidx[:, None], :, posw, :].set(
                    ks.swapaxes(1, 2), mode="drop")
                V8 = V8.at[nidx[:, None], :, posw, :].set(
                    v8.swapaxes(1, 2), mode="drop")
                Vs = Vs.at[nidx[:, None], :, posw, :].set(
                    vs.swapaxes(1, 2), mode="drop")
                Kc, Vc = (K8, Ks), (V8, Vs)
                Kq, Vq, Ksc, Vsc = K8, V8, Ks, Vs
            else:
                kp = self._pack(kn, n, k)           # (n,Hp,k,P*D)
                vp = self._pack(vn, n, k)
                Kc = Kc.at[nidx[:, None], :, posw, :].set(
                    kp.swapaxes(1, 2), mode="drop")
                Vc = Vc.at[nidx[:, None], :, posw, :].set(
                    vp.swapaxes(1, 2), mode="drop")
                Kq, Vq, Ksc, Vsc = Kc, Vc, None, None
            Q2 = self._pack_q_multi(q, n, k)
            O2 = flash_decode(Q2, Kq, Vq, lens_att, scale=self.scale,
                              k_scales=Ksc, v_scales=Vsc, groups=G,
                              q_tokens=k, use_kernel=use_kernel)
            o = self._unpack_o_multi(O2.astype(x.dtype), n, k)
            h = h + _mm(o, bp["Wo"]) + bp["bo"]
            x = ln(h, bp["g2"], bp["b2"])
            h = h + self.mlp(bp, x, li)
            new_caches.append((Kc, Vc))
        logits = _mm(ln(h, p["gf"], p["bf"]), p["head"])
        return logits, new_caches


def _spec_metrics():
    """Speculative-decoding metrics, spelled out for the static lint
    (verdict= values are members of SPEC_VERDICTS; kv_dtype= values of
    KV_DTYPES via kv_label)."""
    from . import observe
    return {
        "tokens": observe.counter(
            "singa_spec_tokens_total",
            "speculative-decoding tokens by verdict (drafted / "
            "accepted / bonus / wasted)"),
        "rounds": observe.counter(
            "singa_spec_rounds_total",
            "speculative verify rounds (one draft+verify cycle)"),
        "acceptance": observe.gauge(
            "singa_spec_acceptance_rate",
            "last call/sync's accepted-over-drafted fraction"),
    }


def record_spec(drafted: int, accepted: int, bonus: int, rounds: int):
    """Book one spec-decoding call/sync's draft economics into the
    singa_spec_* metrics. Returns the acceptance fraction (None when
    nothing was drafted)."""
    from . import observe
    rate = accepted / drafted if drafted > 0 else None
    if not observe.is_enabled():
        return rate
    m = _spec_metrics()
    if drafted:
        m["tokens"].inc(float(drafted), verdict="drafted")
        m["tokens"].inc(float(accepted), verdict="accepted")
        m["tokens"].inc(float(drafted - accepted), verdict="wasted")
    if bonus:
        m["tokens"].inc(float(bonus), verdict="bonus")
    if rounds:
        m["rounds"].inc(float(rounds))
    if rate is not None:
        m["acceptance"].set(rate)
    return rate


def _set_col(buf, i, vals):
    """buf (B,K,L) with column `i` (traced index) set to vals (B,K)."""
    from jax import lax
    return lax.dynamic_update_slice_in_dim(
        buf, vals[..., None], i, axis=2)


def _pool_merge(pool_tok, pool_norm, pool_raw, cand_tok, cand_norm,
                cand_raw, K):
    """Merge candidate finished hypotheses into the K-slot pool, keeping
    the K best by normalized score. Shapes: pool (B,K,L)/(B,K); cand
    (B,kk,L)/(B,kk). Candidates not actually finished carry NEG norm."""
    import jax.numpy as jnp
    all_norm = jnp.concatenate([pool_norm, cand_norm], axis=1)
    all_raw = jnp.concatenate([pool_raw, cand_raw], axis=1)
    all_tok = jnp.concatenate([pool_tok, cand_tok], axis=1)
    from jax import lax
    top_norm, pick = lax.top_k(all_norm, K)
    new_raw = jnp.take_along_axis(all_raw, pick, axis=1)
    new_tok = jnp.take_along_axis(all_tok, pick[..., None], axis=1)
    return new_tok, top_norm, new_raw


def _decode_core(m, S0, max_new, moe_capacity_factor=None, kv_dtype=None):
    """Build the _DecodeCore matching model `m`'s static config."""
    H = m.blocks[0].attn.num_heads
    kv = m.blocks[0].attn.num_kv_heads
    T = S0 + max_new
    assert T <= m.max_seq, \
        f"prompt {S0} + new {max_new} exceeds max_seq {m.max_seq}"
    # decode-time capacity override: capacity-limited routing is a
    # batch-global effect, so cached decode == full forward only in the
    # no-drop regime; a tight TRAINING capacity_factor shouldn't silently
    # drop tokens at serving time — pass moe_capacity_factor (e.g.
    # float(num_experts) for guaranteed no drops) to generate()/
    # generate_beam() to decouple the two.
    moe_ks = [(b.moe.k, float(moe_capacity_factor
                              if moe_capacity_factor is not None
                              else b.moe.capacity_factor))
              if b.moe_experts else None for b in m.blocks]
    return _DecodeCore(H, m.dim, S0, T, (m.dim // H) ** -0.5, moe_ks,
                       kv_heads=kv,
                       rope=(getattr(m, "pos_encoding", "learned")
                             == "rope"),
                       rope_theta=getattr(m, "rope_theta", 10000.0),
                       kv_dtype=kv_dtype)


# ---- decode-param preparation + memo ------------------------------------

def decode_raw(m):
    """Every parameter array the decode consumes — the identity basis for
    the fused/cast decode tree's memo."""
    if not m._pos_init:
        raise RuntimeError(
            "generate() needs initialized weights - call "
            "Model.compile([ids], ...) (or run a forward) first")
    arrs = [m.tok_embed.W.data, m.ln_f.gamma.data, m.ln_f.beta.data]
    if m.pos_encoding != "rope":
        arrs.append(m.pos_embed.data)
    if m.head is not None:
        arrs.append(m.head.W.data)
    for b in m.blocks:
        arrs += [b.ln1.gamma.data, b.ln1.beta.data,
                 b.ln2.gamma.data, b.ln2.beta.data,
                 b.attn.Wq.data, b.attn.Wk.data, b.attn.Wv.data,
                 b.attn.Wo.data]
        if b.attn.use_bias:
            arrs += [b.attn.bq.data, b.attn.bk.data, b.attn.bv.data,
                     b.attn.bo.data]
        if b.moe_experts:
            arrs += [b.moe.Wg.data, b.moe.W1.data, b.moe.b1.data,
                     b.moe.W2.data, b.moe.b2.data]
        else:
            arrs += [b.fc1.W.data, b.fc1.b.data,
                     b.fc2.W.data, b.fc2.b.data]
    return arrs


def _live_refs(arrs):
    """Weakrefs to the param buffers when supported (a freed buffer then
    invalidates the memo deterministically — id() reuse after GC cannot
    produce a false hit); falls back to strong refs, which pin the old
    buffers alive so their ids stay unique until the next decode_state
    call rebuilds the cache."""
    try:
        return tuple(weakref.ref(a) for a in arrs), True
    except TypeError:
        return tuple(arrs), False


def decode_state(m, dtype):
    """Memoized decode-param tree per serving dtype: the QKV fusion, bf16
    cast, and int8 quantization run once per weight set instead of on
    every generate() call. The memo key holds (weak) references to the
    live param buffers and hits only while every buffer is IDENTICAL
    (`is`) to the referenced one — replacing any param (set_params /
    load_checkpoint / load_gpt2_weights) misses deterministically, with
    no reliance on id() non-reuse."""
    arrs = decode_raw(m)
    cached = getattr(m, "_param_cache", None)
    if cached is not None:
        refs, weak, _ = cached
        live = (a() if weak else a for a in refs)
        if len(refs) != len(arrs) or \
                any(r is not a for r, a in zip(live, arrs)):
            cached = None
    if cached is None:
        refs, weak = _live_refs(arrs)
        cached = m._param_cache = (refs, weak, {})
    trees = cached[2]
    if dtype not in trees:
        trees[dtype] = _cast_params(decode_params(m), dtype)
    return trees[dtype]


def decode_params(m):
    """The functional decode-param tree for model `m` (fp32, unfused
    biases zero-filled, QKV fused, head tied/truncated under vocab_tp)."""
    if not m._pos_init:
        raise RuntimeError(
            "generate() needs initialized weights - call "
            "Model.compile([ids], ...) (or run a forward) first")
    import jax.numpy as jnp
    blocks = []
    zeros = jnp.zeros((m.dim,), m.blocks[0].attn.Wq.data.dtype)
    for b in m.blocks:
        ab = b.attn.use_bias
        bp = {
            "g1": b.ln1.gamma.data, "b1": b.ln1.beta.data,
            # fused QKV: one (E,3E) weight stream per block instead of
            # three — fewer ops on the bandwidth-bound decode path
            "Wqkv": jnp.concatenate(
                [b.attn.Wq.data, b.attn.Wk.data, b.attn.Wv.data],
                axis=1),
            "bqkv": jnp.concatenate(
                [b.attn.bq.data, b.attn.bk.data, b.attn.bv.data])
            if ab else jnp.zeros(
                (b.attn.Wq.shape[1] + b.attn.Wk.shape[1]
                 + b.attn.Wv.shape[1],), zeros.dtype),
            "Wo": b.attn.Wo.data,
            "bo": b.attn.bo.data if ab else zeros,
            "g2": b.ln2.gamma.data, "b2": b.ln2.beta.data,
        }
        if b.moe_experts:
            # routing degree/capacity stay STATIC on _DecodeCore
            # (moe_ks), not in the traced param tree
            bp.update({
                "moeWg": b.moe.Wg.data,
                "moeW1": b.moe.W1.data, "moeb1": b.moe.b1.data,
                "moeW2": b.moe.W2.data, "moeb2": b.moe.b2.data,
            })
        else:
            bp.update({
                "W1": b.fc1.W.data, "bb1": b.fc1.b.data,
                "W2": b.fc2.W.data, "bb2": b.fc2.b.data,
            })
        blocks.append(bp)
    emb = m.tok_embed.W.data
    if m.vocab_tp:
        # tied head, truncated to the true vocab so padded rows (never
        # trained toward anything) cannot win an argmax during decode
        head = emb[:m.vocab_size].T
    else:
        head = m.head.W.data
    return {
        "emb": emb,
        "pos": (jnp.zeros((m.max_seq, 0), emb.dtype)
                if m.pos_encoding == "rope"
                else m.pos_embed.data),
        "gf": m.ln_f.gamma.data, "bf": m.ln_f.beta.data,
        "head": head, "blocks": blocks,
    }


# ---- decode-loop builders -----------------------------------------------

def build_decode(m, B, S0, max_new, temperature, top_k,
                 dtype=None, moe_capacity_factor=None, kv_dtype=None):
    """Greedy/sampled decode fn: (params, prompt, key) -> ids.

    Two jitted stages instead of one fused program: `prefill` (causal
    pass + first sampled token) and the `lax.scan` decode loop. The seam
    is where serving telemetry lives — time-to-first-token is the fenced
    prefill stage, tokens/sec the whole call (observe.record_decode) —
    and it is also where a real server would emit the first token. The
    KV caches stay on device between the stages (no host copy), at the
    cost of one cache-sized device copy per call: the scan carry must
    init from immutable input buffers (donation cannot remove it — XLA
    donation is input->output aliasing and the stage outputs only the
    tiny token array). Amortized over max_new tokens; the math is
    op-for-op identical to the previously fused program.
    """
    import time as _time

    import jax
    import jax.numpy as jnp
    from jax import lax

    from . import observe

    core = _decode_core(m, S0, max_new, moe_capacity_factor,
                        kv_dtype=kv_dtype)

    def sample(logits, key):
        logits = logits.astype(jnp.float32)
        if temperature == 0.0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        logits = logits / temperature
        if top_k is not None:
            kth = jnp.sort(logits, axis=-1)[..., -top_k][..., None]
            logits = jnp.where(logits < kth, -jnp.inf, logits)
        return jax.random.categorical(key, logits).astype(jnp.int32)

    def prefill_stage(p, prompt, key):
        # p arrives pre-cast/quantized (decode_state memo)
        logits0, caches = core.prefill(p, prompt, B)
        key, sub = jax.random.split(key)
        tok0 = sample(logits0, sub)                   # (B,)
        # NaN-logit watch (singa_tpu.health): a poisoned checkpoint or a
        # numerics bug shows up here first — count in-graph, one scalar
        nf0 = jnp.sum((~jnp.isfinite(logits0)).astype(jnp.int32))
        return tok0, caches, key, nf0

    def scan_stage(p, tok0, caches, key, nf0):
        # ---- decode: one token per scan step, O(T) attention ----
        def step(carry, i):
            tok, caches, key, nf = carry
            logits, caches = core.token_step(p, tok, caches, i, B)
            nf = nf + jnp.sum((~jnp.isfinite(logits)).astype(jnp.int32))
            key, sub = jax.random.split(key)
            nxt = sample(logits, sub)
            return (nxt, caches, key, nf), nxt

        (_, _, _, nf), toks = lax.scan(
            step, (tok0, caches, key, nf0), jnp.arange(max_new - 1))
        return jnp.concatenate([tok0[:, None], toks.T], axis=1), nf

    # AOT-staged dispatch (singa_tpu.introspect): each distinct abstract
    # signature is built through explicit trace/lower/compile stages, so
    # serving compiles land in singa_compile_phase_seconds and a rebuilt
    # decode fn (new batch/prompt/max_new) produces a recompile-blame
    # record instead of a silent jit retrace. With the warm store
    # enabled (singa_tpu.warmstart), each build also persists its
    # serialized executable keyed by this name + abstract-signature
    # fingerprint — a restarted process (replica respawn, resilience
    # resume) re-stages these same serving executables from disk and
    # its compile phase collapses to near zero
    from . import introspect
    prefill_jit = introspect.AotExecutor(
        jax.jit(prefill_stage), "serving.prefill",
        names=("params", "prompt", "key"))
    scan_jit = introspect.AotExecutor(
        jax.jit(scan_stage), "serving.decode_scan",
        names=("params", "tok0", "caches", "key", "nf"))

    def decode(p, prompt, key):
        # the sync fences exist only to take honest TTFT/latency samples;
        # with observability disabled the stages dispatch fully async
        # (observe.py's "record_* are no-ops when disabled" contract).
        # The outer serving.decode span covers the WHOLE call — including
        # the host-side seams between stages — so the goodput tracker
        # books full serving wall time as productive; the nested stage
        # spans net out of it.
        obs = observe.is_enabled()
        from . import resilience, slo, watchdog
        # an installed SLO tracker needs honest fenced samples even
        # with the metric hooks disabled — the tracker was installed
        # on purpose, and silently starving it of records would make
        # /slo read "no data" for exactly one of the two serving modes
        sample = obs or slo.get_tracker() is not None
        # the watchdog's `decode` deadline arms over the whole call
        # (prefill + scan + the host seams); `serving.decode` is its
        # deterministic FaultPlan hook
        with watchdog.guard("decode", batch=B), \
                observe.span("serving.decode", batch=B,
                             new_tokens=max_new):
            resilience.fault_point("serving.decode", batch=B)
            t0 = _time.perf_counter()
            ttft = None
            with observe.span("serving.prefill", batch=B,
                              prompt_tokens=S0):
                tok0, caches, key, nf = prefill_jit(p, prompt, key)
                if sample:
                    jax.block_until_ready(tok0)
                    ttft = _time.perf_counter() - t0
            # memory-ledger birth-site hook: the per-block KV caches
            # are live host-visible buffers only at this seam (the
            # fused beam program never surfaces its caches) — the
            # ledger's serving.decode snapshot attributes them here.
            # Gated on an installed ledger: without a consumer, the
            # per-array weakref churn would tax every decode call.
            # When a serving engine's page pool owns the kv_cache
            # region (a persistent provider), the transient note is
            # superseded — the pool provider is authoritative and the
            # per-call weakref churn buys nothing
            from . import memory
            if memory.get_ledger() is not None and \
                    not memory.region_has_provider(
                        memory.REGION_KV_CACHE):
                memory.note_arrays(memory.REGION_KV_CACHE, caches)
            if max_new > 1:
                with observe.span("serving.decode_scan", batch=B,
                                  new_tokens=max_new):
                    toks, nf = scan_jit(p, tok0, caches, key, nf)
            else:
                toks = tok0[:, None]
            ids = jnp.concatenate([prompt if isinstance(prompt, jax.Array)
                                   else jnp.asarray(prompt), toks], axis=1)
            if sample:
                jax.block_until_ready(ids)
                kind = "greedy" if temperature == 0.0 else "sampled"
                total = _time.perf_counter() - t0
                if obs:
                    observe.record_decode(
                        kind, total, new_tokens=B * max_new,
                        batch=B, ttft=ttft, prompt_tokens=B * S0)
                    from . import health
                    health.record_nan_logits(int(jax.device_get(nf)),
                                             kind)
                # SLO wiring: the dense path's calls count toward the
                # declared serving objectives too (latency/rate/TTFT),
                # so /slo answers for static-batch deployments —
                # note_decode is a no-op without a tracker
                slo.note_decode(kind, total, B * max_new, ttft=ttft,
                                batch=B)
        return ids

    return decode


def build_spec_decode(m, draft, B, S0, max_new, spec_k, dtype=None,
                      moe_capacity_factor=None, kv_dtype=None,
                      use_kernel=None):
    """Draft-model speculative GREEDY decode fn:
    (target_params, draft_params, prompt) -> (ids, stats).

    Each round: the small draft model proposes `spec_k` tokens
    sequentially against its own KV cache, the target verifies ALL of
    them in ONE batched forward (verify_step: spec_k+1 tokens through
    the cache, the causal ladder), and the longest accepted prefix plus
    the target's own next token commit — 1..spec_k+1 tokens per round
    at ~one decode step's weight traffic. Greedy-equivalence is exact
    by construction: every committed token IS the target's argmax given
    the committed prefix (the spec==greedy test enforces token-for-token
    identity with build_decode's output). Per-row variable acceptance
    rides an active mask + per-row positions, so the verify executable
    compiles ONCE (a single lax.while_loop program).

    The draft runs an fp KV cache regardless of the target's
    `kv_dtype` — draft proposals only gate ACCEPTANCE, never
    correctness, and the draft cache is small."""
    import time as _time

    import jax
    import jax.numpy as jnp
    from jax import lax

    from . import observe

    assert spec_k >= 1, spec_k
    K = int(spec_k)
    core = _decode_core(m, S0, max_new, moe_capacity_factor,
                        kv_dtype=kv_dtype)
    core_d = _decode_core(draft, S0, max_new, moe_capacity_factor,
                          kv_dtype=None)

    def prefill_stage(pt, pd, prompt):
        logits0, caches = core.prefill(pt, prompt, B)
        _dl, dcaches = core_d.prefill(pd, prompt, B)   # logits unused:
        # the first token is the TARGET's — the draft only fills its
        # own KV cache over the prompt here
        tok0 = jnp.argmax(logits0.astype(jnp.float32),
                          axis=-1).astype(jnp.int32)
        nf0 = jnp.sum((~jnp.isfinite(logits0)).astype(jnp.int32))
        return tok0, caches, dcaches, nf0

    def spec_stage(pt, pd, tok0, caches, dcaches, nf0):
        nidx = jnp.arange(B)
        buf = jnp.zeros((B, max_new), jnp.int32).at[:, 0].set(tok0)
        zero = jnp.int32(0)

        def cond(c):
            return jnp.any(c[1] < max_new)

        def body(c):
            buf, cnt, tok, caches, dcaches, nf, drafted, accepted, \
                bonus, rounds = c
            active = cnt < max_new
            pos = S0 + cnt - 1          # the pending token's position

            def dstep(carry, j):
                dt, dc = carry
                lg, dc = core_d.verify_step(
                    pd, dt[:, None], dc, pos + j, active, B, 1,
                    use_kernel=use_kernel)
                nxt = jnp.argmax(lg[:, 0].astype(jnp.float32),
                                 axis=-1).astype(jnp.int32)
                return (nxt, dc), nxt

            # K+1 draft steps for K proposals: the extra step feeds
            # d_K so the draft cache writes row pos+K too — when all
            # K drafts accept (take = K+1, the bonus token commits at
            # pos+K+1), that row would otherwise stay a ZERO hole the
            # draft attends over forever after, silently degrading
            # every later proposal's acceptance
            (_, dcaches), drafts = lax.scan(
                dstep, (tok, dcaches), jnp.arange(K + 1))
            drafts = drafts[:K].T                   # (B, K)
            feed = jnp.concatenate([tok[:, None], drafts], axis=1)
            logits, caches = core.verify_step(
                pt, feed, caches, pos, active, B, K + 1,
                use_kernel=use_kernel)
            g = jnp.argmax(logits.astype(jnp.float32),
                           axis=-1).astype(jnp.int32)  # (B, K+1)
            match = (g[:, :K] == drafts).astype(jnp.int32)
            a = jnp.sum(jnp.cumprod(match, axis=1), axis=1)  # (B,)
            take = jnp.where(active,
                             jnp.minimum(a + 1, max_new - cnt), 0)
            j = jnp.arange(K + 1)[None, :]
            idx = jnp.where(j < take[:, None], cnt[:, None] + j,
                            max_new)
            buf = buf.at[nidx[:, None], idx].set(g, mode="drop")
            tok = jnp.where(active,
                            g[nidx, jnp.clip(take - 1, 0, K)], tok)
            cnt = cnt + take
            # nf: only logits whose tokens commit (the rest are
            # ladder positions past this row's budget — garbage by
            # construction, not a health signal)
            nf = nf + jnp.sum(((~jnp.isfinite(logits))
                               & (j < take[:, None])[..., None])
                              .astype(jnp.int32))
            n_act = jnp.sum(active.astype(jnp.int32))
            drafted = drafted + K * n_act
            # a budget-truncated round (take <= a) commits ONLY
            # accepted draft tokens — the bonus token exists only
            # when the full a+1 window committed
            bo_i = ((take > 0) & (take > a)).astype(jnp.int32)
            accepted = accepted + jnp.sum(take - bo_i)
            bonus = bonus + jnp.sum(bo_i)
            return (buf, cnt, tok, caches, dcaches, nf, drafted,
                    accepted, bonus, rounds + 1)

        init = (buf, jnp.full((B,), 1, jnp.int32), tok0, caches,
                dcaches, nf0, zero, zero, zero, zero)
        buf, _, _, _, _, nf, drafted, accepted, bonus, rounds = \
            lax.while_loop(cond, body, init) if max_new > 1 else init
        return buf, nf, drafted, accepted, bonus, rounds

    from . import introspect
    prefill_jit = introspect.AotExecutor(
        jax.jit(prefill_stage), "serving.spec_prefill",
        names=("params", "draft_params", "prompt"))
    spec_jit = introspect.AotExecutor(
        jax.jit(spec_stage), "serving.spec_verify",
        names=("params", "draft_params", "tok0", "caches",
               "draft_caches", "nf"))

    def decode(pt, pd, prompt):
        from . import resilience, slo, watchdog
        obs = observe.is_enabled()
        sample = obs or slo.get_tracker() is not None
        with watchdog.guard("decode", batch=B), \
                observe.span("serving.decode", batch=B,
                             new_tokens=max_new, spec_k=K):
            resilience.fault_point("serving.decode", batch=B)
            t0 = _time.perf_counter()
            ttft = None
            with observe.span("serving.prefill", batch=B,
                              prompt_tokens=S0):
                tok0, caches, dcaches, nf = prefill_jit(pt, pd, prompt)
                if sample:
                    jax.block_until_ready(tok0)
                    ttft = _time.perf_counter() - t0
            from . import memory
            if memory.get_ledger() is not None and \
                    not memory.region_has_provider(
                        memory.REGION_KV_CACHE):
                memory.note_arrays(memory.REGION_KV_CACHE,
                                   (caches, dcaches))
            with observe.span("serving.spec_verify", batch=B,
                              new_tokens=max_new):
                toks, nf, drafted, accepted, bonus, rounds = spec_jit(
                    pt, pd, tok0, caches, dcaches, nf)
            ids = jnp.concatenate(
                [prompt if isinstance(prompt, jax.Array)
                 else jnp.asarray(prompt), toks], axis=1)
            if sample:
                jax.block_until_ready(ids)
                total = _time.perf_counter() - t0
                drafted, accepted, bonus, rounds = (
                    int(v) for v in jax.device_get(
                        (drafted, accepted, bonus, rounds)))
                record_spec(drafted, accepted, bonus, rounds)
                if obs:
                    observe.record_decode(
                        "spec", total, new_tokens=B * max_new,
                        batch=B, ttft=ttft, prompt_tokens=B * S0)
                    from . import health
                    health.record_nan_logits(int(jax.device_get(nf)),
                                             "spec")
                slo.note_decode("spec", total, B * max_new, ttft=ttft,
                                batch=B)
        return ids

    return decode


def build_beam_decode(m, B, S0, max_new, num_beams, length_penalty,
                      eos_id, dtype, pad_id=None, moe_capacity_factor=None,
                      kv_dtype=None):
    """Jitted beam-search decode fn: (params, prompt) -> (ids, score)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    V = m.vocab_size
    K = num_beams
    core = _decode_core(m, S0, max_new, moe_capacity_factor,
                        kv_dtype=kv_dtype)
    NEG = jnp.float32(-1e9)
    pad = 0 if eos_id is None else (pad_id if pad_id is not None
                                    else eos_id)

    def norm_len(score, length):
        return score / (length.astype(jnp.float32) ** length_penalty)

    def decode(p, prompt):
        # p arrives pre-cast/quantized (decode_state memo)
        # ---- prefill on the B prompts, then tile caches to B*K ----
        logits0, caches = core.prefill(p, prompt, B)
        # beam b*K+k from prompt b (tree-map: kv8 caches are
        # (int8, scales) tuples)
        caches = jax.tree.map(lambda a: jnp.repeat(a, K, axis=0),
                              caches)
        logp0 = jax.nn.log_softmax(
            logits0.astype(jnp.float32), axis=-1)     # (B,V)
        nf = jnp.sum((~jnp.isfinite(logits0)).astype(jnp.int32))
        tokens = jnp.full((B, K, max_new), pad, jnp.int32)
        # finished-hypothesis pool (HF-style): finished beams move
        # here with a length-normalized score and stop competing by
        # raw score against still-growing beams
        pool_tok = jnp.full((B, K, max_new), pad, jnp.int32)
        pool_norm = jnp.full((B, K), NEG)
        pool_raw = jnp.full((B, K), NEG)

        if eos_id is None:
            s0, t0 = lax.top_k(logp0, K)              # (B,K)
            alive_scores = s0
            tokens = tokens.at[:, :, 0].set(t0)
        else:
            # consider 2K candidates so K alive beams survive even if
            # eos ranks high
            kk = min(2 * K, V)
            cs, ct = lax.top_k(logp0, kk)             # (B,kk)
            is_eos = ct == eos_id
            # finished at length 1 -> pool
            cand_pool_tok = jnp.broadcast_to(
                jnp.full((max_new,), pad, jnp.int32)
                .at[0].set(eos_id)[None, None],
                (B, kk, max_new))
            pool_tok, pool_norm, pool_raw = _pool_merge(
                pool_tok, pool_norm, pool_raw,
                cand_pool_tok,
                jnp.where(is_eos, norm_len(cs, jnp.asarray(1)), NEG),
                cs, K)
            # alive beams: best K non-eos
            alive_cs = jnp.where(is_eos, NEG, cs)
            s0, pick = lax.top_k(alive_cs, K)         # (B,K) of [0,kk)
            t0 = jnp.take_along_axis(ct, pick, axis=1)
            alive_scores = s0
            tokens = tokens.at[:, :, 0].set(t0)

        def step(carry, i):
            tokens, scores, caches, pool_tok, pool_norm, pool_raw, nf = \
                carry
            tok = lax.dynamic_index_in_dim(
                tokens, i, axis=2, keepdims=False)    # (B,K)
            logits, caches = core.token_step(
                p, tok.reshape(B * K), caches, i, B * K)
            nf = nf + jnp.sum((~jnp.isfinite(logits)).astype(jnp.int32))
            logp = jax.nn.log_softmax(
                logits.astype(jnp.float32), axis=-1).reshape(B, K, V)
            total = scores[..., None] + logp          # (B,K,V)
            flat = total.reshape(B, K * V)
            kk = min(2 * K, K * V)
            cs, idx = lax.top_k(flat, kk)             # (B,kk)
            beam_idx = idx // V
            cand_tok = (idx % V).astype(jnp.int32)
            gather = jnp.take_along_axis
            cand_hist = gather(tokens, beam_idx[..., None], axis=1)
            cand_hist = _set_col(cand_hist, i + 1, cand_tok)

            if eos_id is not None:
                is_eos = cand_tok == eos_id
                pool_tok, pool_norm, pool_raw = _pool_merge(
                    pool_tok, pool_norm, pool_raw, cand_hist,
                    jnp.where(is_eos,
                              norm_len(cs, jnp.asarray(i + 2)), NEG),
                    cs, K)
                cs = jnp.where(is_eos, NEG, cs)
            new_scores, pick = lax.top_k(cs, K)       # (B,K)
            keep_beam = gather(beam_idx, pick, axis=1)
            tokens = gather(cand_hist, pick[..., None], axis=1)
            src = (jnp.arange(B)[:, None] * K
                   + keep_beam).reshape(B * K)        # flat rows
            caches = jax.tree.map(lambda a: a[src], caches)
            return (tokens, new_scores, caches,
                    pool_tok, pool_norm, pool_raw, nf), None

        carry = (tokens, alive_scores, caches,
                 pool_tok, pool_norm, pool_raw, nf)
        if max_new > 1:
            carry, _ = lax.scan(step, carry, jnp.arange(max_new - 1))
        tokens, scores, _, pool_tok, pool_norm, pool_raw, nf = carry

        # final selection: best of {pool, alive} by normalized score
        alive_norm = norm_len(scores, jnp.asarray(max_new))
        all_norm = jnp.concatenate([pool_norm, alive_norm], axis=1)
        all_raw = jnp.concatenate([pool_raw, scores], axis=1)
        all_tok = jnp.concatenate([pool_tok, tokens], axis=1)
        best = jnp.argmax(all_norm, axis=1)           # (B,)
        out = jnp.take_along_axis(
            all_tok, best[:, None, None], axis=1)[:, 0]
        best_score = jnp.take_along_axis(
            all_raw, best[:, None], axis=1)[:, 0]
        return jnp.concatenate([prompt, out], axis=1), best_score, nf

    from . import introspect
    jitted = introspect.AotExecutor(
        jax.jit(decode), "serving.beam", names=("params", "prompt"))

    def run(p, prompt):
        import time as _time

        from . import observe, slo
        obs = observe.is_enabled()
        if not obs and slo.get_tracker() is None:
            # no fence, no record: pure dispatch
            ids, score, _nf = jitted(p, prompt)
            return ids, score
        t0 = _time.perf_counter()
        from . import watchdog
        with watchdog.guard("decode", batch=B), \
                observe.span("serving.beam_decode", batch=B, beams=K):
            ids, score, nf = jitted(p, prompt)
            jax.block_until_ready(ids)
        # one fused program: no prefill seam, so no TTFT sample here
        total = _time.perf_counter() - t0
        if obs:
            observe.record_decode("beam", total, new_tokens=B * max_new,
                                  batch=B, prompt_tokens=B * S0)
            from . import health
            health.record_nan_logits(int(jax.device_get(nf)), "beam")
        slo.note_decode("beam", total, B * max_new, batch=B)
        return ids, score

    return run


def poisson_workload(seed, n_req, rps, vocab, prompt_lens, new_lens,
                     new_dist="bimodal"):
    """The seeded Poisson serving workload shared by `bench_decode
    --serve`, `slo --ab`, and the router's kill-and-replace harness
    (all three of its arms — clean, kill, and the FaultPlan-delayed
    tail-attribution arm replay the same schedule, which is what makes
    the /tailz and cold-vs-warm comparisons apples-to-apples):
    exponential inter-arrival times at `rps`, uniform prompt lengths in
    `prompt_lens = (lo, hi)`, and output lengths in `new_lens = (lo,
    hi)` — bimodal by default (75% short / 25% long, the mix that keeps
    a continuous-batching engine's slots ragged). Fully determined by
    `seed`: two arms replaying the same workload submit byte-identical
    prompts at identical offsets, which is what makes A/B comparisons
    (and the router's token-identity failover assert) meaningful.

    Returns {"arrivals": float array of cumulative offsets (s),
    "prompts": list of int32 prompt arrays, "new_lens": int array}.
    """
    import numpy as np
    p_lo, p_hi = (int(x) for x in prompt_lens)
    n_lo, n_hi = (int(x) for x in new_lens)
    n_req = int(n_req)
    rng = np.random.RandomState(seed)
    arrivals = np.cumsum(rng.exponential(1.0 / float(rps), n_req))
    prompts = [rng.randint(0, int(vocab),
                           (rng.randint(p_lo, p_hi + 1),)).astype(np.int32)
               for _ in range(n_req)]
    if new_dist == "bimodal":
        short_hi = max(n_lo + 1, n_lo + (n_hi - n_lo) // 4)
        long_lo = max(short_hi, n_hi - (n_hi - n_lo) // 8)
        is_long = rng.rand(n_req) < 0.25
        lens = np.where(is_long,
                        rng.randint(long_lo, n_hi + 1, n_req),
                        rng.randint(n_lo, short_hi + 1, n_req))
    else:
        lens = rng.randint(n_lo, n_hi + 1, n_req)
    return {"arrivals": arrivals, "prompts": prompts, "new_lens": lens}


__all__ = ["build_decode", "build_beam_decode", "build_spec_decode",
           "decode_state", "decode_params", "decode_raw",
           "KV_DTYPES", "SPEC_VERDICTS", "kv_label", "record_spec",
           "poisson_workload"]
