"""Compile & memory introspection: recompile blame, AOT cost/memory
telemetry, and the `explain` report.

On TPUs the two dominant invisible costs are XLA compilation and HBM.
PR 1 *counts* recompiles (`singa_model_recompile_total`) without saying
why one happened, and nothing reported flops/step or the HBM breakdown —
the "fast as the hardware allows" goal was unmeasurable. This module is
the build-time half of observability, in three parts:

1. **Recompile blame.** Every AOT build records the executable's abstract
   call signature (leaf shapes/dtypes, step tag, static args, donation
   set). When a later build for the same key arrives, the new signature
   is diffed against the nearest prior one and a structured reason is
   emitted — `singa_recompile_total{reason=...}` with a FIXED
   low-cardinality enum (`RECOMPILE_REASONS`) plus a detail string
   ("arg `arg0` batch 32->48 crossed bucket 32->64") into the EventLog.

2. **AOT cost/memory telemetry.** `build_compiled` routes a jitted
   callable through the explicit `trace -> lower -> compile` stages,
   timing each phase into `singa_compile_phase_seconds{phase=...}`, and
   harvests `compiled.cost_analysis()` / `memory_analysis()` into
   `singa_xla_flops_per_step`, `singa_xla_bytes_accessed` and the
   `singa_hbm_{arguments,outputs,temps,generated_code}_bytes` gauges.
   The step build also populates `Device.cost_analysis` (un-deadening
   `PrintTimeProfiling` verbosity>=2) and registers a per-step callback
   that derives `singa_mfu_pct` from the platform peak-flops table
   (override: `set_peak_tflops` / `SINGA_TPU_PEAK_TFLOPS` /
   `config.PEAK_TFLOPS`). All of this happens at build/retrace time —
   the cached step path dispatches the same executable bytes `jax.jit`
   would have cached, with zero added per-step work.

3. **`explain` report.** `python -m singa_tpu.introspect` (reusing
   bench.py's model builders) prints params, GFLOPs/step, the HBM
   breakdown, compile-phase times, recompile history, and — given an
   xplane dir — the top-K ops by device time (`xprof.top_ops`).
   `capture_hlo(dir)` additionally dumps each executable's HLO text
   (manifest + fingerprint); FlightRecorder bundles reference the
   manifest so an anomaly dump pins the exact executable.

4. **Warm staging (singa_tpu.warmstart).** When the warm store is
   enabled (`SINGA_TPU_COMPILE_CACHE` / `warmstart.enable`),
   `build_compiled` looks the (key, signature-fingerprint) pair up in
   the serialized-executable store before staging
   (`load_executable`) and, on a fresh build, exports the jitted
   callable into it (`export_executable`). Both cold and warm builds
   then stage through the exported module's round-trip, so the XLA
   persistent cache key is identical across process lifetimes — a
   restarted replica's "compile" is a disk read. Every lookup result
   (hit|miss|stale|corrupt) is counted, recorded on the build record,
   and emitted with the compile/recompile EventLog record. With the
   store disabled (the default) the staging path is bit-unchanged.
"""

from __future__ import annotations

import hashlib
import json
import os
import time

from . import config, observe

# ---- enums (the lint in tools/check_metrics_names.py greps these) ---------

#: Low-cardinality blame reasons for `singa_recompile_total{reason=...}`.
#: batch_bucket: only a leading (batch) dim changed — the detail string
#:   names the power-of-two batch-size class crossed (PR 1's framing).
#: shape: a non-batch dim changed. dtype: a leaf dtype flipped.
#: new_step_tag: a different static step tag (DistOpt partial rotation).
#: static_args / arg_count / donation: the non-array signature changed.
#: new_function: an identical signature rebuilt from a fresh callable
#:   (e.g. a re-built serving decode fn for the same shapes).
#: unknown: none of the tracked fields differ — should not appear in
#:   practice; its presence is itself a signal the blame logic is blind.
RECOMPILE_REASONS = ("batch_bucket", "shape", "dtype", "new_step_tag",
                     "static_args", "arg_count", "donation",
                     "new_function", "unknown")
REASON_BATCH_BUCKET = "batch_bucket"
REASON_SHAPE = "shape"
REASON_DTYPE = "dtype"
REASON_NEW_STEP_TAG = "new_step_tag"
REASON_STATIC_ARGS = "static_args"
REASON_ARG_COUNT = "arg_count"
REASON_DONATION = "donation"
REASON_NEW_FUNCTION = "new_function"
REASON_UNKNOWN = "unknown"

#: Build phases for `singa_compile_phase_seconds{phase=...}`: trace (the
#: python step function -> jaxpr), lower (jaxpr -> StableHLO), compile
#: (the XLA backend build — on TPU by far the dominant term).
COMPILE_PHASES = ("trace", "lower", "compile")
PHASE_TRACE = "trace"
PHASE_LOWER = "lower"
PHASE_COMPILE = "compile"

#: Executable keys (the `key=` label on the gauges/histograms above).
EXEC_KEYS = ("step", "eval", "serving.prefill", "serving.decode_scan",
             "serving.beam")

# ---- per-platform peaks (public spec sheets; shared with bench.py) --------

#: Dense bf16 peak TFLOP/s by TPU generation.
PEAK_TFLOPS_BF16 = [
    ("v6", 918.0), ("trillium", 918.0),
    ("v5p", 459.0),
    ("v5 lite", 197.0), ("v5e", 197.0), ("v5litepod", 197.0),
    ("v5", 459.0),
    ("v4", 275.0),
    ("v3", 123.0),
    ("v2", 45.0),
]

#: HBM bandwidth GB/s by generation (roofline readouts).
PEAK_HBM_GBS = [
    ("v6", 1638.0), ("trillium", 1638.0),
    ("v5p", 2765.0),
    ("v5 lite", 819.0), ("v5e", 819.0), ("v5litepod", 819.0),
    ("v5", 2765.0),
    ("v4", 1228.0),
    ("v3", 900.0),
    ("v2", 700.0),
]


def chip_peak(device_kind: str, table):
    kind = (device_kind or "").lower()
    for key, peak in table:
        if key in kind:
            return peak
    return None


_peak_override: "float | None" = None


def set_peak_tflops(v: "float | None"):
    """Override the platform peak used by the MFU gauge (None = table)."""
    global _peak_override
    _peak_override = float(v) if v else None
    return _peak_override


def peak_tflops(device_kind: "str | None" = None) -> "float | None":
    """Peak TFLOP/s for MFU: explicit override > SINGA_TPU_PEAK_TFLOPS /
    config.PEAK_TFLOPS > the per-generation table for `device_kind`."""
    if _peak_override is not None:
        return _peak_override
    cfg = getattr(config, "PEAK_TFLOPS", None)
    if cfg:
        return float(cfg)
    kind = device_kind if device_kind is not None else _step_device_kind
    return chip_peak(kind or "", PEAK_TFLOPS_BF16)


# ---- state -----------------------------------------------------------------

MAX_HISTORY = 64

_history: dict = {}    # key -> [signature dicts]
_builds: dict = {}     # key -> [build records]
_blames: list = []     # chronological blame records
_manifest: list = []   # executable manifest ({key, fingerprint, hlo_path})
_hlo_dir: "str | None" = None
_step_flops = 0.0
_step_device_kind = ""


def reset():
    """Clear all introspection state (tests: the conftest metric-isolation
    fixture calls this next to MetricsRegistry.reset)."""
    global _hlo_dir, _step_flops, _step_device_kind, _peak_override
    _history.clear()
    _builds.clear()
    del _blames[:]
    del _manifest[:]
    _hlo_dir = None
    _step_flops = 0.0
    _step_device_kind = ""
    _peak_override = None
    observe.set_step_callback(None)


# ---- abstract call signatures ---------------------------------------------

def _aval(a):
    shape = getattr(a, "shape", None)
    dt = getattr(a, "dtype", None)
    return (tuple(shape) if shape is not None else (),
            str(dt) if dt is not None else type(a).__name__)


def signature(args, names=None, tag=None, static=None, donated=(),
              batch_hint=None):
    """Abstract call signature of a positional-arg tuple: one
    (name, shape, dtype) entry per array leaf (containers expand to
    `name0`, `name1`, ...), plus the non-array dimensions a retrace can
    key on — step tag, static-arg repr, donation set, and the true batch
    size (`batch_hint`) when the traced leading dim is a padded bucket."""
    import jax
    leaves = []
    seq = args if isinstance(args, (tuple, list)) else (args,)
    for i, a in enumerate(seq):
        nm = names[i] if names and i < len(names) else f"a{i}"
        if isinstance(a, (tuple, list, dict)):
            flat, _ = jax.tree_util.tree_flatten(a)
            for j, leaf in enumerate(flat):
                leaves.append((f"{nm}{j}",) + _aval(leaf))
        else:
            leaves.append((nm,) + _aval(a))
    return {"tag": tag, "static": static, "donated": tuple(donated),
            "leaves": leaves,
            "batch_hint": int(batch_hint) if batch_hint else None}


def _bucket(n) -> int:
    """Power-of-two batch-size class containing n (PR 1's batch_class)."""
    n = int(n)
    return n if n <= 1 else 1 << (n - 1).bit_length()


def blame(prev: dict, cur: dict):
    """Diff two signatures into (reason, detail). `reason` is always a
    member of RECOMPILE_REASONS; `detail` is the human-readable one-liner
    that lands in the EventLog record."""
    if prev.get("tag") != cur.get("tag"):
        return (REASON_NEW_STEP_TAG,
                f"step tag {prev.get('tag')}->{cur.get('tag')}")
    if prev.get("static") != cur.get("static"):
        return (REASON_STATIC_ARGS,
                f"static args {prev.get('static')}->{cur.get('static')}")
    if prev.get("donated") != cur.get("donated"):
        return (REASON_DONATION,
                f"donated argnums {prev.get('donated')}"
                f"->{cur.get('donated')}")
    pl = {n: (s, d) for n, s, d in prev["leaves"]}
    cl = {n: (s, d) for n, s, d in cur["leaves"]}
    if set(pl) != set(cl):
        added = sorted(set(cl) - set(pl))[:4]
        gone = sorted(set(pl) - set(cl))[:4]
        return (REASON_ARG_COUNT,
                f"{len(pl)}->{len(cl)} array args"
                + (f" (+{','.join(added)})" if added else "")
                + (f" (-{','.join(gone)})" if gone else ""))
    for n, cs, cd in cur["leaves"]:
        ps, pd = pl[n]
        if pd != cd:
            return REASON_DTYPE, f"arg `{n}` dtype {pd}->{cd}"
    for n, cs, cd in cur["leaves"]:
        ps, _pd = pl[n]
        if ps == cs:
            continue
        if ps and cs and len(ps) == len(cs) and ps[1:] == cs[1:]:
            ho = prev.get("batch_hint") or ps[0]
            hn = cur.get("batch_hint") or cs[0]
            bo, bn = _bucket(ho), _bucket(hn)
            if bo != bn:
                return (REASON_BATCH_BUCKET,
                        f"arg `{n}` batch {ho}->{hn} "
                        f"crossed bucket {bo}->{bn}")
            return (REASON_BATCH_BUCKET,
                    f"arg `{n}` batch {ho}->{hn} within bucket {bn}")
        return REASON_SHAPE, f"arg `{n}` shape {ps}->{cs}"
    return (REASON_NEW_FUNCTION,
            "identical signature rebuilt from a fresh callable")


def _nearest(history, sig):
    """The prior signature with the fewest differences from `sig`, so the
    blame names what actually changed rather than diffing against an
    arbitrary ancestor (e.g. a long-gone step tag)."""
    best, best_score = None, None
    for prev in reversed(history):
        score = 0
        if prev.get("tag") != sig.get("tag"):
            score += 100
        if prev.get("static") != sig.get("static"):
            score += 100
        pl = {n: (s, d) for n, s, d in prev["leaves"]}
        cl = {n: (s, d) for n, s, d in sig["leaves"]}
        score += 10 * len(set(pl) ^ set(cl))
        score += sum(1 for n in set(pl) & set(cl) if pl[n] != cl[n])
        if best_score is None or score < best_score:
            best, best_score = prev, score
            if score == 0:
                break
    return best


# ---- metric plumbing (enum-guarded: see tools/check_metrics_names.py) -----

def _count_recompile(reason, key):
    if reason not in RECOMPILE_REASONS:
        reason = REASON_UNKNOWN
    if observe.is_enabled():
        observe.counter(
            "singa_recompile_total",
            "retraces after the first compile, by structured blame reason"
        ).inc(reason=reason, key=key)


def _observe_phase(phase, key, seconds):
    assert phase in COMPILE_PHASES, phase
    if observe.is_enabled():
        observe.histogram(
            "singa_compile_phase_seconds",
            "AOT build wall seconds per phase (trace|lower|compile)"
        ).observe(seconds, phase=phase, key=key)


def compile_phase_totals() -> dict:
    """{phase: total wall seconds} accumulated so far in
    singa_compile_phase_seconds, summed across build keys — the
    replica cold-start observatory diffs two samples of this to know
    how much of a startup window went to trace/lower/compile (vs the
    python-side model build around them). Zeros before any build (or
    with observe disabled)."""
    out = {p: 0.0 for p in COMPILE_PHASES}
    h = observe.get_registry().get("singa_compile_phase_seconds")
    if h is None:
        return out
    for row in h.snapshot():
        ph = (row.get("labels") or {}).get("phase")
        if ph in out:
            out[ph] += float(row.get("sum") or 0.0)
    return out


def _set_hbm_gauges(mem, key):
    # spelled out (no loop over a name table) so the static metric-name
    # lint sees every registration
    if not observe.is_enabled():
        return
    if "arguments" in mem:
        observe.gauge("singa_hbm_arguments_bytes",
                      "executable argument-buffer bytes"
                      ).set(float(mem["arguments"]), key=key)
    if "outputs" in mem:
        observe.gauge("singa_hbm_outputs_bytes",
                      "executable output-buffer bytes"
                      ).set(float(mem["outputs"]), key=key)
    if "temps" in mem:
        observe.gauge("singa_hbm_temps_bytes",
                      "executable temporary-buffer bytes"
                      ).set(float(mem["temps"]), key=key)
    if "generated_code" in mem:
        observe.gauge("singa_hbm_generated_code_bytes",
                      "executable generated-code bytes"
                      ).set(float(mem["generated_code"]), key=key)


def note_step_flops(flops):
    """Record the flops of the step executable actually being dispatched
    (model.py calls this on variant switch), so MFU is computed with the
    running variant's flops rather than the most recently BUILT one —
    a partial-batch build must not skew later full-batch readings."""
    global _step_flops
    _step_flops = float(flops or 0.0)


def _mfu_callback(seconds):
    """Fed each step's wall seconds by observe.record_step (un-fenced
    dispatch time) and record_step_fenced (honest device latency, when
    verbosity profiling is on). Un-fenced dispatch on an async backend
    can return in microseconds while the device still computes; in
    steady state it converges to the true step time (the loop is
    device-throughput-bound), but a sample implying more than the
    hardware peak is physically impossible and is DROPPED rather than
    poisoning the gauge — the same mfu_suspect guard bench.py applies."""
    peak = peak_tflops(_step_device_kind)
    if not peak or not _step_flops or seconds <= 0:
        return
    mfu = _step_flops / seconds / 1e12 / peak * 100.0
    if mfu > 100.0 and _peak_override is None:
        return  # async-dispatch artifact, not physics
    observe.gauge(
        "singa_mfu_pct",
        "model flops utilization of the last step, percent of the "
        "platform bf16 peak (flops/step / step_seconds / peak)"
    ).set(mfu)


# ---- harvesting ------------------------------------------------------------

def _harvest_cost(compiled) -> dict:
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return {}
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return dict(ca or {})


def _harvest_memory(compiled, args) -> dict:
    mem = {}
    try:
        ma = compiled.memory_analysis()
    except Exception:
        ma = None
    if ma is not None:
        for field, name in (("argument_size_in_bytes", "arguments"),
                            ("output_size_in_bytes", "outputs"),
                            ("temp_size_in_bytes", "temps"),
                            ("generated_code_size_in_bytes",
                             "generated_code")):
            v = getattr(ma, field, None)
            if v is not None:
                mem[name] = int(v)
    if not mem.get("arguments"):
        # backends without memory stats: the argument bytes at least are
        # always derivable from the abstract inputs
        import jax
        flat, _ = jax.tree_util.tree_flatten(args)
        mem["arguments"] = int(sum(
            int(getattr(a, "nbytes", 0) or 0) for a in flat))
    return mem


def _write_hlo(compiled, key, fingerprint):
    try:
        text = compiled.as_text()
    except Exception:
        return None
    try:
        os.makedirs(_hlo_dir, exist_ok=True)
        safe = key.replace(".", "_").replace("/", "_")
        sha = hashlib.sha256(text.encode()).hexdigest()[:16]
        path = os.path.join(_hlo_dir, f"{safe}_{sha}.hlo.txt")
        if not os.path.exists(path):
            with open(path, "w", encoding="utf-8") as f:
                f.write(text)
        with open(os.path.join(_hlo_dir, "manifest.jsonl"), "a",
                  encoding="utf-8") as f:
            f.write(json.dumps(
                {"key": key, "fingerprint": fingerprint, "hlo_sha": sha,
                 "path": path, "ts": round(time.time(), 6)}) + "\n")
        return path
    except OSError:
        return None


def capture_hlo(dir_path: "str | None"):
    """Enable (path) or disable (None) per-executable HLO-text capture.
    Each later build writes `<key>_<sha>.hlo.txt` plus a `manifest.jsonl`
    line under the directory; the in-memory `executable_manifest()` (and
    through it every FlightRecorder bundle header) carries the paths."""
    global _hlo_dir
    _hlo_dir = str(dir_path) if dir_path else None
    return _hlo_dir


def executable_manifest():
    """Every AOT-built executable this process has seen: {key,
    fingerprint, hlo_path (when capture_hlo was on), ts}."""
    return [dict(e) for e in _manifest]


def latest_fingerprint(key: str) -> "str | None":
    """The newest manifest fingerprint for `key`, or None before any
    build. regress.py anchors each latency baseline to this: a baseline
    whose fingerprint no longer matches is compile-cause evidence, and
    only fingerprint-MATCHED baselines compare across restarts."""
    for e in reversed(_manifest):
        if e.get("key") == key:
            return e.get("fingerprint")
    return None


def last_build(key: str) -> "dict | None":
    """The most recent build record for `key` (phases, cost, memory,
    blame) — bench.py --explain reads this."""
    recs = _builds.get(key)
    return dict(recs[-1]) if recs else None


def blame_history():
    """Chronological recompile-blame records ({key, reason, detail, ...})."""
    return [dict(b) for b in _blames]


# ---- the AOT build ---------------------------------------------------------

def _sig_fingerprint(key: str, sig: dict) -> str:
    """16-hex fingerprint of (key, abstract call signature) — the
    identity executables are manifested, blamed, and warm-stored
    under. Deliberately signature-based rather than HLO-based: it must
    be computable BEFORE any staging, so a warm restart can look the
    store up without first paying the trace the store exists to skip."""
    return hashlib.sha256(
        (key + "|" + json.dumps(
            {"tag": sig.get("tag"), "static": sig.get("static"),
             "donated": list(sig.get("donated") or ()),
             "leaves": [[n, list(s), d] for n, s, d in sig["leaves"]]},
            sort_keys=True, default=str)).encode()).hexdigest()[:16]


def _stage(fn, args):
    """Explicit trace -> lower -> compile of one jitted callable, with
    per-phase wall timing. Raises whatever the staging machinery
    raises; callers decide the fallback."""
    t0 = time.perf_counter()
    if hasattr(fn, "trace"):
        traced = fn.trace(*args)
        t1 = time.perf_counter()
        lowered = traced.lower()
    else:
        # pre-0.4.30 jax: no Traced stage; trace+lower in one call
        t1 = t0
        lowered = fn.lower(*args)
    t2 = time.perf_counter()
    compiled = lowered.compile()
    t3 = time.perf_counter()
    return compiled, {"trace": t1 - t0, "lower": t2 - t1,
                      "compile": t3 - t2}


def export_executable(fn, args, key, fingerprint) -> "bytes | None":
    """Serialize jitted `fn` specialized to the concrete `args` tuple
    (jax.export, version-gated in _compat) and write it into the warm
    store under (key, fingerprint). Returns the blob, or None when the
    store is disabled, this jax cannot export, the function resists
    exporting, or the store write fails — in every case the caller
    simply proceeds without persistence."""
    from . import _compat, warmstart
    store = warmstart.get_store()
    if store is None:
        return None
    blob = _compat.serialize_executable(fn, args)
    if blob is None:
        return None
    if store.save(key, fingerprint, blob) is None:
        return None
    return blob


def load_executable(key, fingerprint, *, count: bool = True):
    """Load + deserialize the warm-store entry for (key, fingerprint).
    Returns (callable, result, seconds): the callable is a jit-wrapped
    deserialized module ready for `_stage` (None unless `result` is
    "hit"), and result is a member of warmstart.CACHE_RESULTS — or
    (None, None, 0.0) with the store disabled. Integrity failures
    (unreadable meta, sha-256 mismatch, undeserializable blob) classify
    as corrupt; a meta whose fingerprint or jax version does not match
    classifies as stale; both delete the entry so the fresh rebuild
    re-exports a clean replacement. With count=False the caller records
    the classification itself (`build_compiled` does, after staging
    confirms the artifact actually compiles)."""
    from . import _compat, warmstart
    store = warmstart.get_store()
    if store is None:
        return None, None, 0.0
    t0 = time.perf_counter()
    blob, result = store.load(key, fingerprint)
    warm_fn = None
    if blob is not None:
        warm_fn = _compat.deserialize_executable(blob)
        if warm_fn is None:
            result = warmstart.RESULT_CORRUPT
            store.discard(key, fingerprint)
    seconds = time.perf_counter() - t0
    if count:
        warmstart.note_lookup(key, fingerprint, result, seconds)
    return warm_fn, result, seconds


def build_compiled(fn, args, key, sig=None, device=None):
    """Build `fn` (a jax.jit-wrapped callable) for `args` through the
    explicit trace -> lower -> compile stages.

    Times each phase into `singa_compile_phase_seconds`, harvests cost /
    memory analysis into the `singa_xla_*` / `singa_hbm_*` gauges,
    registers the signature for recompile blame, and returns
    (compiled_executable, build_record). Returns (None, None) when AOT
    staging fails for any reason — the caller falls back to the plain jit
    call, so telemetry can never break dispatch.

    With the warm store enabled (singa_tpu.warmstart), staging goes
    through the serialized-executable layer: a warm build loads the
    stored blob and stages its deserialized module (near-zero trace;
    compile is an XLA persistent-cache disk hit), a cold build exports
    first and stages the same round-trip so the persistent cache is
    seeded under the process-stable module key, and any stale/corrupt
    entry — or a warm artifact that fails to stage — falls back to the
    fresh path and re-exports. The lookup classification lands on the
    build record (`warm`) and the EventLog compile record.
    """
    from . import _compat, warmstart
    if sig is None:
        sig = signature(args)
    fingerprint = _sig_fingerprint(key, sig)
    warmstart.maybe_enable_from_env()
    warm_result = None
    warm_fn = None
    load_s = 0.0
    if warmstart.is_enabled():
        # separate leaf span, also mapped to the goodput `compile`
        # bucket: a warm restart's disk time is still compile-bucket
        # time — there is just ~none of it
        with observe.span("introspect.warm_load", key=key):
            warm_fn, warm_result, load_s = load_executable(
                key, fingerprint, count=False)
    compiled = phases = None
    # span -> the goodput `compile` bucket (and nets out of any mapped
    # enclosing span, e.g. a first-call model.eval)
    with observe.span("introspect.build", key=key):
        if warm_fn is not None:
            try:
                compiled, phases = _stage(warm_fn, args)
            except Exception:
                # deserialized but will not stage on this backend: the
                # same trust verdict as a bad blob — drop the entry and
                # rebuild fresh below (which re-exports a replacement)
                warm_result = warmstart.RESULT_CORRUPT
                st = warmstart.get_store()
                if st is not None:
                    st.discard(key, fingerprint)
        if compiled is None and warmstart.is_enabled():
            # cold build WITH the store: export first, then stage the
            # deserialized round-trip — one compile that (a) proves the
            # stored blob reproduces, and (b) seeds the XLA persistent
            # cache with the exact module a warm restart stages (the
            # exported module's cache key is stable across processes;
            # the original python callable's is not)
            blob = export_executable(fn, args, key, fingerprint)
            rt = _compat.deserialize_executable(blob) if blob else None
            if rt is not None:
                try:
                    compiled, phases = _stage(rt, args)
                except Exception:
                    compiled = None
        if compiled is None:
            try:
                compiled, phases = _stage(fn, args)
            except Exception:
                return None, None
    if warm_result is not None:
        warmstart.note_lookup(key, fingerprint, warm_result, load_s)
    _observe_phase(PHASE_TRACE, key, phases["trace"])
    _observe_phase(PHASE_LOWER, key, phases["lower"])
    _observe_phase(PHASE_COMPILE, key, phases["compile"])
    cost = _harvest_cost(compiled)
    mem = _harvest_memory(compiled, args)
    if observe.is_enabled():
        observe.gauge("singa_xla_flops_per_step",
                      "XLA cost-analysis flops of the compiled executable"
                      ).set(float(cost.get("flops", 0.0) or 0.0), key=key)
        observe.gauge("singa_xla_bytes_accessed",
                      "XLA cost-analysis bytes accessed per execution"
                      ).set(float(cost.get("bytes accessed", 0.0) or 0.0),
                            key=key)
        _set_hbm_gauges(mem, key)
    hlo_path = _write_hlo(compiled, key, fingerprint) if _hlo_dir else None
    rec = {"key": key, "fingerprint": fingerprint, "phases": phases,
           "cost": cost, "memory": mem, "hlo_path": hlo_path,
           "warm": warm_result,
           "ts": round(time.time(), 6)}
    _register_build(key, sig, rec, device=device)
    return compiled, rec


def _register_build(key, sig, rec, device=None):
    hist = _history.setdefault(key, [])
    recompile = bool(hist)
    reason = detail = None
    if recompile:
        reason, detail = blame(_nearest(hist, sig), sig)
        _count_recompile(reason, key)
        _blames.append({"key": key, "reason": reason, "detail": detail,
                        "fingerprint": rec["fingerprint"],
                        "ts": rec["ts"]})
        del _blames[:-4 * MAX_HISTORY]
    hist.append(sig)
    del hist[:-MAX_HISTORY]
    rec.update({"recompile": recompile, "reason": reason, "detail": detail})
    _builds.setdefault(key, []).append(rec)
    del _builds[key][:-MAX_HISTORY]
    _manifest.append({"key": key, "fingerprint": rec["fingerprint"],
                      "hlo_path": rec["hlo_path"], "ts": rec["ts"]})
    del _manifest[:-4 * MAX_HISTORY]
    if observe.is_enabled():
        observe.get_registry().emit({
            "kind": "recompile" if recompile else "compile",
            "key": key, "reason": reason, "detail": detail,
            "fingerprint": rec["fingerprint"],
            "phases": {k: round(v, 6) for k, v in rec["phases"].items()},
            "flops": rec["cost"].get("flops"),
            # warm-store classification (hit|miss|stale|corrupt), None
            # when the store is disabled — the recompile-blame EventLog
            # doubles as the warm-start audit trail
            "warm": rec.get("warm"),
        })
    if key == "step":
        global _step_flops, _step_device_kind
        _step_flops = float(rec["cost"].get("flops", 0.0) or 0.0)
        if device is not None:
            _step_device_kind = getattr(
                device.jax_device, "device_kind", "") or ""
            if rec["cost"]:
                # refresh on EVERY step build (not just the first): after
                # a retrace, PrintTimeProfiling must report the current
                # variant's flops, and an empty {} seeded by the model's
                # profiling fallback must not pin the field forever
                device.cost_analysis = dict(rec["cost"])
        if _step_flops > 0:
            observe.set_step_callback(_mfu_callback)


_AOT_MISS = object()  # "no cache entry" (a stored None = negative-cached)


class AotExecutor:
    """Wrap a jitted callable so every distinct abstract signature is
    built through `build_compiled` (phase timing, cost/memory harvest,
    recompile blame) and later calls dispatch the cached executable.
    Falls back to the plain jit call when staging or dispatch fails —
    jit then (re)traces exactly as it always did; a failed signature is
    negative-cached so the fallback never re-pays staging per call."""

    __slots__ = ("fn", "key", "names", "donated", "_execs")

    def __init__(self, fn, key, names=None, donated=()):
        self.fn = fn
        self.key = key
        self.names = names
        # the jit's donate_argnums, recorded into every signature this
        # executor registers: donation is part of the compiled module's
        # identity (input-output aliasing), so the warm store must not
        # key a donated variant and an undonated one identically
        self.donated = tuple(donated)
        self._execs = {}

    def _sig_key(self, args):
        import jax
        flat, _ = jax.tree_util.tree_flatten(args)
        return tuple(_aval(a) for a in flat)

    def __call__(self, *args):
        k = self._sig_key(args)
        ex = self._execs.get(k, _AOT_MISS)
        if ex is _AOT_MISS:
            sig = signature(args, names=self.names,
                            donated=self.donated)
            ex, _rec = build_compiled(self.fn, args, self.key, sig)
            self._execs[k] = ex  # None negative-caches failed staging
            if ex is None:
                # fresh staging failure: this jit call compiles cold —
                # the mapped span books it to the goodput `compile`
                # bucket instead of the enclosing serving/step span
                with observe.span("model.jit_fallback"):
                    return self.fn(*args)
        if ex is None:
            return self.fn(*args)
        try:
            return ex(*args)
        except Exception as exec_exc:
            from . import memory
            if memory.is_resource_exhausted(exec_exc):
                # device allocator exhausted: the jit fallback would
                # re-pay the same allocation and die the same way —
                # dump the OOM forensics bundle and let it propagate
                memory.handle_oom(exec_exc, key=self.key)
                raise
            self._execs[k] = None
            with observe.span("model.jit_fallback"):
                return self.fn(*args)


# ---- the explain report ----------------------------------------------------

def explain(model=None, device=None, xplane=None, top=10) -> dict:
    """Gather everything this module knows into one report dict:
    per-key build records, recompile history, the executable manifest,
    and (given a model/device) params, GFLOPs/step, the HBM breakdown,
    mean step time, achieved TFLOP/s and MFU; with `xplane`, the top-K
    ops by measured device time."""
    import numpy as np
    rep = {
        "builds": {k: [dict(r) for r in v] for k, v in _builds.items()},
        "recompiles": blame_history(),
        "executables": executable_manifest(),
    }
    if model is not None:
        try:
            rep["params"] = int(sum(
                int(np.prod(t.shape)) if t.shape else 1
                for t in model.get_params().values()))
        except Exception:
            pass
    step = last_build("step")
    flops = 0.0
    if step:
        flops = float(step["cost"].get("flops", 0.0) or 0.0)
        rep["gflops_per_step"] = flops / 1e9
        rep["bytes_accessed_per_step"] = float(
            step["cost"].get("bytes accessed", 0.0) or 0.0)
        rep["hbm"] = dict(step.get("memory") or {})
        rep["compile_phases_s"] = {
            k: round(v, 6) for k, v in (step.get("phases") or {}).items()}
        rep["fingerprint"] = step.get("fingerprint")
    if device is not None and device.step_times:
        mean_s = sum(device.step_times) / len(device.step_times)
        rep["step_ms_mean"] = mean_s * 1e3
        if flops and mean_s > 0:
            ach = flops / mean_s / 1e12
            rep["achieved_tflops"] = ach
            peak = peak_tflops(
                getattr(device.jax_device, "device_kind", ""))
            if peak:
                rep["peak_tflops"] = peak
                rep["mfu_pct"] = ach / peak * 100.0
    if xplane:
        from . import xprof
        rep["top_ops"] = [
            {"op": r["op"], "category": r["category"],
             "total_ms": round(r["total_ms"], 3),
             "pct": round(r["pct"], 1)}
            for r in xprof.top_ops(xplane, top)]
    # the dynamic half of the memory model (singa_tpu.memory): live
    # region breakdown when a ledger is installed, and the pre-flight
    # fit estimate combining this module's static analysis with the
    # ledger's measured param+opt bytes
    try:
        from . import memory
        led = memory.get_ledger()
        if led is not None and led.timeline:
            rep["mem_regions"] = dict(led.timeline[-1]["regions"])
        if model is not None:
            rep["memory_fit"] = memory.estimate_fit(model=model,
                                                    device=device)
    except Exception:
        pass
    return rep


def _mb(b):
    return f"{(b or 0) / 1e6:.2f} MB"


def format_explain(rep: dict) -> str:
    lines = ["== singa_tpu introspect: compile & memory explain =="]
    if "params" in rep:
        lines.append(f"params: {rep['params'] / 1e6:.3f} M")
    if "gflops_per_step" in rep:
        lines.append(f"step executable [{rep.get('fingerprint', '?')}]: "
                     f"{rep['gflops_per_step']:.4f} GFLOP/step, "
                     f"{_mb(rep.get('bytes_accessed_per_step'))} accessed")
    ph = rep.get("compile_phases_s")
    if ph:
        lines.append("  compile phases: " + "  ".join(
            f"{p} {ph.get(p, 0.0):.3f}s" for p in COMPILE_PHASES))
    hbm = rep.get("hbm")
    if hbm:
        lines.append("  HBM: " + " | ".join(
            f"{k} {_mb(v)}" for k, v in sorted(hbm.items())))
    if "step_ms_mean" in rep:
        tail = ""
        if "achieved_tflops" in rep:
            tail = f" -> {rep['achieved_tflops']:.4f} TFLOP/s achieved"
            if "mfu_pct" in rep:
                tail += (f" (MFU {rep['mfu_pct']:.2f}% of "
                         f"{rep['peak_tflops']:g} peak)")
        lines.append(f"  step time: {rep['step_ms_mean']:.3f} ms mean"
                     + tail)
    for key, recs in sorted(rep.get("builds", {}).items()):
        if key == "step":
            continue
        r = recs[-1]
        fl = float(r["cost"].get("flops", 0.0) or 0.0)
        lines.append(f"{key} executable [{r['fingerprint']}]: "
                     f"{fl / 1e9:.4f} GFLOP, compile "
                     f"{r['phases'].get('compile', 0.0):.3f}s")
    mr = rep.get("mem_regions")
    if mr:
        live = " | ".join(f"{k} {_mb(v)}" for k, v in sorted(mr.items())
                          if v)
        lines.append(f"  live memory (ledger): {live or 'empty'}")
    fit = rep.get("memory_fit")
    if fit:
        lim = fit.get("limit_bytes")
        lines.append(
            f"  memory fit: est peak {_mb(fit['estimated_peak_bytes'])}"
            + (f" vs limit {_mb(lim)} -> "
               f"{'fits' if fit['fits'] else 'DOES NOT FIT'}"
               if lim else " (device limit unknown)"))
    blames = rep.get("recompiles", [])
    lines.append(f"recompile history ({len(blames)}):")
    for b in blames:
        lines.append(f"  [{b['key']}] {b['reason']}: {b['detail']}")
    execs = rep.get("executables", [])
    if execs:
        lines.append(f"executables ({len(execs)}):")
        for e in execs:
            lines.append(f"  {e['key']}@{e['fingerprint']}"
                         + (f"  hlo: {e['hlo_path']}" if e.get("hlo_path")
                            else ""))
    tops = rep.get("top_ops")
    if tops:
        lines.append(f"top {len(tops)} ops by device time (xplane):")
        for r in tops:
            lines.append(f"  {r['op'][:60]:<60} {r['total_ms']:>8.3f} ms "
                         f"{r['pct']:>5.1f}%")
    return "\n".join(lines)


# ---- CLI: python -m singa_tpu.introspect ----------------------------------

_CLI_PRESETS = {
    # reuse bench.py's builders (build_bench_model) so the explain report
    # describes the exact executables the bench times
    "tiny": dict(model="mlp", batch=8, size=16),
    "mlp": dict(model="mlp", batch=32, size=64),
    "cnn": dict(model="cnn", batch=4, size=28),
    "resnet18": dict(model="resnet18", batch=4, size=32),
    "gpt": dict(model="gpt", batch=2, size=64,
                gpt_dim=128, gpt_layers=2, gpt_heads=4),
}


def _build_cli_model(cfg: str):
    import sys
    try:
        import bench
    except ImportError:
        sys.path.insert(0, os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        import bench
    return bench.build_bench_model(**_CLI_PRESETS[cfg])


def main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        prog="python -m singa_tpu.introspect",
        description="Compile & memory explain report: build a bench "
                    "model, run a few steps through the AOT-staged path, "
                    "and print GFLOPs/step, the HBM breakdown, "
                    "compile-phase times and the recompile history.")
    ap.add_argument("--config", default="tiny",
                    choices=sorted(_CLI_PRESETS))
    ap.add_argument("--steps", type=int, default=3)
    ap.add_argument("--no-retrace", dest="retrace", action="store_false",
                    default=True,
                    help="skip the 3/4-batch re-step that demonstrates "
                         "recompile blame")
    ap.add_argument("--xplane", default=None, metavar="DIR",
                    help="xplane trace dir: append the top-K ops by "
                         "measured device time (xprof.top_ops)")
    ap.add_argument("--top", type=int, default=10)
    ap.add_argument("--hlo-dir", default=None, metavar="DIR",
                    help="capture each executable's HLO text + manifest")
    ap.add_argument("--peak-tflops", type=float, default=None,
                    help="override the platform peak for the MFU line")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    import numpy as np
    import jax
    from . import opt as opt_mod, tensor
    if args.peak_tflops:
        set_peak_tflops(args.peak_tflops)
    if args.hlo_dir:
        capture_hlo(args.hlo_dir)
    m, tx, ty, _items, _unit, _factory = _build_cli_model(args.config)
    dev = tx.device
    m.set_optimizer(opt_mod.SGD(lr=0.1, momentum=0.9))
    m.compile([tx], is_train=True, use_graph=True)
    dev.SetVerbosity(1)
    dev.SetSkipIteration(0)
    for _ in range(max(args.steps, 1)):
        m(tx, ty)
    b = int(tx.shape[0])
    if args.retrace and b >= 4:
        nb = (3 * b) // 4
        x2 = np.asarray(jax.device_get(tx.data))[:nb]
        y2 = np.asarray(jax.device_get(ty.data))[:nb]
        m(tensor.Tensor(data=x2, device=dev),
          tensor.from_numpy(y2, device=dev))
    rep = explain(model=m, device=dev, xplane=args.xplane, top=args.top)
    if args.json:
        print(json.dumps(rep, default=str))
    else:
        print(format_explain(rep))
    return 0


__all__ = [
    "RECOMPILE_REASONS", "COMPILE_PHASES", "EXEC_KEYS",
    "PEAK_TFLOPS_BF16", "PEAK_HBM_GBS", "chip_peak",
    "set_peak_tflops", "peak_tflops",
    "signature", "blame", "build_compiled", "AotExecutor",
    "export_executable", "load_executable",
    "note_step_flops",
    "capture_hlo", "executable_manifest", "latest_fingerprint",
    "last_build", "blame_history",
    "compile_phase_totals",
    "explain", "format_explain", "reset", "main",
]


if __name__ == "__main__":
    import sys as _sys
    # run through the canonical package module so CLI state (hlo capture,
    # peak override) and the model's build records live in ONE instance
    from singa_tpu import introspect as _canonical
    _sys.exit(_canonical.main())
