"""Optimizers + distributed training strategies.

Reference parity: python/singa/opt.py — `DecayScheduler/Constant/
ExponentialDecay` (opt.py:28-68); `Optimizer` with tensor-valued hyperparams
living inside the training step (:71-171); `SGD` (momentum/nesterov/
dampening/weight-decay, :174-333), `RMSProp` (:336), `AdaGrad` (:444),
`Adam` (:536); `DistOpt` (:686) with four strategies: plain fused allreduce
(:826), fp16 (:867), partial update (:922), sparsified w/ error feedback
(:994).

TPU-native redesign: gradients come from the tape generator
(autograd.backward) so communication can start per-gradient, exactly like
the reference; collectives are `lax.psum`/`all_gather` bound to the mesh
axis of Model's shard_map step (parallel/communicator.py) instead of NCCL
stream calls. Optimizer state are Tensors threaded through the jitted step
(buffer donation = the reference's in-place Axpy update).
"""

from __future__ import annotations

import time

import numpy as np
import jax.numpy as jnp

from . import autograd
from . import health
from . import memory
from . import observe
from .tensor import Tensor


def _health_start(loss):
    """Active health collector for this step (None = health off). Feeds
    the loss; the per-(grad, update) feeds sit in each strategy loop so
    the stats see the POST-reduction gradient each strategy actually
    applies — that's the effective update numerics the watchdog guards."""
    col = health.collector()
    if col is not None:
        col.observe_loss(loss.data)
    return col


# ---- learning-rate schedulers (ref opt.py:28-68) -------------------------

class DecayScheduler:
    def __init__(self, init_value: float):
        self.init_value = init_value

    def __call__(self, step):
        raise NotImplementedError


class Constant(DecayScheduler):
    def __call__(self, step):
        return jnp.asarray(self.init_value, dtype=jnp.float32)


class ExponentialDecay(DecayScheduler):
    def __init__(self, init_value, decay_steps, decay_rate, staircase=False):
        super().__init__(init_value)
        self.decay_steps = decay_steps
        self.decay_rate = decay_rate
        self.staircase = staircase

    def __call__(self, step):
        s = step / self.decay_steps
        if self.staircase:
            s = jnp.floor(s)
        return self.init_value * jnp.power(self.decay_rate, s)


def _sched(lr) -> DecayScheduler:
    return lr if isinstance(lr, DecayScheduler) else Constant(float(lr))


# ---- base optimizer ------------------------------------------------------

class Optimizer:
    """Per-param state lives in `self._states[pid]` dicts of jnp arrays; the
    step counter is an array so schedulers trace into the jitted step."""

    def __init__(self, lr):
        self.lr = _sched(lr)
        self.step_counter = jnp.zeros((), dtype=jnp.float32)
        self._states = {}       # id(param) -> {name: array}
        self._state_order = []  # pids in creation order (checkpoint order)

    def step_tag(self) -> int:
        """Static step variant selector consumed by Model's per-tag
        executable cache; plain optimizers have a single variant."""
        return 0

    # -- state plumbing for Model's jitted step ---------------------------
    def state_arrays(self):
        """Flat list of state arrays (stable order) + the step counter."""
        arrs = [self.step_counter]
        for pid in self._state_order:
            for k in sorted(self._states[pid]):
                arrs.append(self._states[pid][k])
        return arrs

    def load_state_arrays(self, arrs):
        self.step_counter = arrs[0]
        i = 1
        for pid in self._state_order:
            for k in sorted(self._states[pid]):
                self._states[pid][k] = arrs[i]
                i += 1

    def get_states(self) -> dict:
        out = {"step_counter": np.asarray(self.step_counter)}
        for j, pid in enumerate(self._state_order):
            for k, v in self._states[pid].items():
                out[f"p{j}.{k}"] = np.asarray(v)
        return out

    def set_states(self, states: dict):
        if "step_counter" in states:
            self.step_counter = jnp.asarray(states["step_counter"])
        for j, pid in enumerate(self._state_order):
            for k in self._states[pid]:
                key = f"p{j}.{k}"
                if key in states:
                    self._states[pid][k] = jnp.asarray(states[key])

    def _state(self, param: Tensor) -> dict:
        pid = id(param)
        if pid not in self._states:
            self._states[pid] = self._init_state(param)
            self._state_order.append(pid)
        return self._states[pid]

    def _init_state(self, param: Tensor) -> dict:
        return {}

    def setup(self, params):
        """Pre-create all per-param state so the jitted step threads concrete
        buffers (the reference creates them lazily on first apply)."""
        params = list(params)
        self._params_by_id = {id(p): p for p in params}
        for p in params:
            self._state(p)
        # memory-ledger birth-site hook: slot buffers + step counter,
        # re-read per snapshot (lazily growing sparse residuals stay
        # covered)
        memory.track_optimizer(self)

    def state_specs(self):
        """PartitionSpec per state_arrays() entry: optimizer state for a
        TP-sharded param is sharded like the param (momentum of a column
        shard is a column shard)."""
        from jax.sharding import PartitionSpec as P
        specs = [P()]  # step counter
        by_id = getattr(self, "_params_by_id", {})
        for pid in self._state_order:
            p = by_id.get(pid)
            spec = getattr(p, "spec", None) if p is not None else None
            for _k in sorted(self._states[pid]):
                specs.append(spec if spec is not None else P())
        return specs

    # -- API ---------------------------------------------------------------
    def __call__(self, loss: Tensor):
        return self.backward_and_update(loss)

    def backward_and_update(self, loss: Tensor):
        # Under graph mode this runs at TRACE time, so the telemetry
        # fires once per compilation (param count + trace cost), not per
        # step — see observe.record_opt_update.
        t0 = time.perf_counter()
        col = _health_start(loss)
        n = 0
        with observe.span("opt.apply_updates"):
            for p, g in autograd.backward(loss):
                old = p.data if col is not None else None
                self.apply(p, g)
                if col is not None:
                    col.observe(p, g.data, old, p.data)
                n += 1
        self.step()
        observe.record_opt_update(n, time.perf_counter() - t0, "local")

    def step(self):
        self.step_counter = self.step_counter + 1.0

    def apply(self, param: Tensor, grad: Tensor):
        raise NotImplementedError

    def device_check(self, *args):
        pass


class SGD(Optimizer):
    """(ref opt.py:174-333)"""

    def __init__(self, lr=0.1, momentum=0.0, dampening=0.0, weight_decay=0.0,
                 nesterov=False):
        super().__init__(lr)
        self.momentum = momentum
        self.dampening = dampening
        self.weight_decay = weight_decay
        self.nesterov = nesterov
        if nesterov and (momentum <= 0 or dampening != 0):
            raise ValueError("nesterov needs momentum>0, dampening=0")

    def _init_state(self, param):
        if self.momentum > 0:
            return {"momentum_buf": jnp.zeros(param.shape, dtype=param.dtype)}
        return {}

    def apply(self, param: Tensor, grad: Tensor):
        g = grad.data
        lr = self.lr(self.step_counter).astype(param.dtype)
        if self.weight_decay > 0:
            g = g + self.weight_decay * param.data
        if self.momentum > 0:
            st = self._state(param)
            buf = self.momentum * st["momentum_buf"] + (1 - self.dampening) * g
            st["momentum_buf"] = buf
            g = g + self.momentum * buf if self.nesterov else buf
        param.data = param.data - lr * g


class RMSProp(Optimizer):
    """(ref opt.py:336)"""

    def __init__(self, lr=0.1, rho=0.9, epsilon=1e-8, weight_decay=0.0):
        super().__init__(lr)
        self.rho = rho
        self.epsilon = epsilon
        self.weight_decay = weight_decay

    def _init_state(self, param):
        return {"running_average": jnp.zeros(param.shape, dtype=param.dtype)}

    def apply(self, param: Tensor, grad: Tensor):
        g = grad.data
        lr = self.lr(self.step_counter).astype(param.dtype)
        if self.weight_decay > 0:
            g = g + self.weight_decay * param.data
        st = self._state(param)
        avg = self.rho * st["running_average"] + (1 - self.rho) * g * g
        st["running_average"] = avg
        param.data = param.data - lr * g / jnp.sqrt(avg + self.epsilon)


class AdaGrad(Optimizer):
    """(ref opt.py:444)"""

    def __init__(self, lr=0.1, epsilon=1e-8, weight_decay=0.0):
        super().__init__(lr)
        self.epsilon = epsilon
        self.weight_decay = weight_decay

    def _init_state(self, param):
        return {"history": jnp.zeros(param.shape, dtype=param.dtype)}

    def apply(self, param: Tensor, grad: Tensor):
        g = grad.data
        lr = self.lr(self.step_counter).astype(param.dtype)
        if self.weight_decay > 0:
            g = g + self.weight_decay * param.data
        st = self._state(param)
        hist = st["history"] + g * g
        st["history"] = hist
        param.data = param.data - lr * g / jnp.sqrt(hist + self.epsilon)


class Adam(Optimizer):
    """(ref opt.py:536)"""

    def __init__(self, lr=0.001, beta_1=0.9, beta_2=0.999, epsilon=1e-8,
                 weight_decay=0.0):
        super().__init__(lr)
        self.beta_1 = beta_1
        self.beta_2 = beta_2
        self.epsilon = epsilon
        self.weight_decay = weight_decay

    def _init_state(self, param):
        return {"m": jnp.zeros(param.shape, dtype=param.dtype),
                "v": jnp.zeros(param.shape, dtype=param.dtype)}

    def apply(self, param: Tensor, grad: Tensor):
        g = grad.data
        lr = self.lr(self.step_counter).astype(param.dtype)
        if self.weight_decay > 0:
            g = g + self.weight_decay * param.data
        st = self._state(param)
        t = self.step_counter + 1.0
        m = self.beta_1 * st["m"] + (1 - self.beta_1) * g
        v = self.beta_2 * st["v"] + (1 - self.beta_2) * g * g
        st["m"], st["v"] = m, v
        mhat = m / (1 - jnp.power(self.beta_1, t)).astype(param.dtype)
        vhat = v / (1 - jnp.power(self.beta_2, t)).astype(param.dtype)
        param.data = param.data - lr * mhat / (jnp.sqrt(vhat) + self.epsilon)


# ---- distributed optimizer (ref opt.py:686-1094) -------------------------

class DistOpt(Optimizer):
    """Synchronous data-parallel wrapper.

    Reference: wraps NCCL `Communicator` with 4 strategies (opt.py:826-1094).
    Here: wraps the mesh-axis communicator (parallel/communicator.py); the
    actual collective is an XLA psum/all_gather over ICI, inserted wherever
    the tape yields a gradient — so late-layer allreduce overlaps remaining
    backward exactly like the reference's 3-stream pipeline, courtesy of
    XLA's latency-hiding scheduler.

    Must run inside Model graph mode (the step is shard_mapped over the
    mesh); `world_size` is the size of the `axis` mesh axis.
    """

    def __init__(self, opt: Optimizer, axis: str = "data", mesh=None,
                 topk_frac: float = 0.01, sparse_residuals: bool = False):
        # NOTE: intentionally not calling super().__init__ — we delegate to
        # the wrapped optimizer's state machinery.
        # sparse_residuals: pre-create error-feedback residual buffers for
        # REPLICATED params at setup() time. Only needed to use
        # backward_and_sparse_update(corr=True) on a model with
        # TP/PP-sharded params (per-leaf state specs cannot grow
        # mid-trace); costs one zero buffer per replicated param, so it
        # is opt-in rather than always-on.
        from .parallel.communicator import Communicator
        self.opt = opt
        self.axis = axis
        self.communicator = Communicator(axis=axis, mesh=mesh)
        self.world_size = self.communicator.world_size
        self.topk_frac = topk_frac
        self.sparse_residuals = sparse_residuals
        self._spars_residual = {}   # id(param) -> error-feedback residual
        self._spars_order = []
        self._partial_counter = 0
        self._partial_mode = False  # set while tracing partial-update
        self.partial_k = 1
        self._partial_static_idx = None  # set by Model per compiled tag

    # delegate scheduler/step state to the inner optimizer
    @property
    def lr(self):
        return self.opt.lr

    @property
    def step_counter(self):
        return self.opt.step_counter

    def setup(self, params):
        self.opt.setup(params)
        # When any param is mesh-sharded, the step compiles with PER-LEAF
        # opt-state specs, so the sparse strategy's error-feedback
        # residuals can no longer appear lazily mid-trace (the pytree
        # would stop matching). With sparse_residuals=True, pre-create
        # them for the REPLICATED params (in TP/PP models those are the
        # small ones — norms, biases — the big sharded params take the
        # dense reduction, see backward_and_sparse_update).
        if not self.sparse_residuals:
            return
        by_id = getattr(self.opt, "_params_by_id", {})
        for pid, p in by_id.items():
            if getattr(p, "spec", None) is None \
                    and pid not in self._spars_residual:
                self._spars_residual[pid] = jnp.zeros(p.shape,
                                                      dtype=p.dtype)
                self._spars_order.append(pid)

    def state_arrays(self):
        arrs = list(self.opt.state_arrays())
        for pid in self._spars_order:
            arrs.append(self._spars_residual[pid])
        return arrs

    def state_specs(self):
        from jax.sharding import PartitionSpec as P
        specs = list(self.opt.state_specs())
        by_id = getattr(self.opt, "_params_by_id", {})
        for pid in self._spars_order:
            p = by_id.get(pid)
            spec = getattr(p, "spec", None) if p is not None else None
            specs.append(spec if spec is not None else P())
        return specs

    def load_state_arrays(self, arrs):
        n_inner = len(self.opt.state_arrays())
        self.opt.load_state_arrays(arrs[:n_inner])
        tail = arrs[n_inner:]
        if tail and len(tail) < len(self._spars_order):
            # e.g. saved and restored with different sparse_residuals
            # settings — positional mapping would misassign
            raise ValueError(
                f"checkpoint has {len(tail)} sparse residuals but the "
                f"optimizer tracks {len(self._spars_order)}; save and "
                "restore with the same sparse_residuals setting")
        if not tail and self._spars_order:
            # rollback to a checkpoint that predates residual creation:
            # exact resume means starting from zero error feedback
            for pid in self._spars_order:
                self._spars_residual[pid] = jnp.zeros_like(
                    self._spars_residual[pid])
        for i, pid in enumerate(self._spars_order):
            if i < len(tail):
                self._spars_residual[pid] = tail[i]
        extra = list(tail[len(self._spars_order):])
        if extra:
            # checkpoint restored before the first backward established
            # the residual order: consumed in creation order by
            # backward_and_sparse_update
            self._pending_residuals = extra

    # -- per-device residual checkpointing --------------------------------
    # Error-feedback residuals are PER-DEVICE state (each data shard keeps
    # its own top-K leftovers) that rides the step under a replicated
    # out-spec — the per-device buffers persist across steps because the
    # step feeds its own outputs back in. A naive save reads device 0's
    # copy only; these two methods save/restore the full (n_dev, ...)
    # stack so checkpoint-resume stays bit-identical. Exact dist resume
    # additionally needs DistOpt(sparse_residuals=True), so the slots are
    # threaded as step INPUTS from step 0 (a lazily-created slot restored
    # into a fresh model would be baked into the first executable as a
    # constant, collapsing the per-device values again).
    def residual_device_stacks(self):
        """{state_arrays index: (n_devices, *shape) numpy} for residuals
        whose per-device buffers differ (multi-device arrays)."""
        import jax
        out = {}
        n_inner = len(self.opt.state_arrays())
        for i, pid in enumerate(self._spars_order):
            a = self._spars_residual[pid]
            if isinstance(a, jax.Array) and len(a.addressable_shards) > 1:
                shards = sorted(a.addressable_shards,
                                key=lambda s: s.device.id)
                out[n_inner + i] = np.stack(
                    [np.asarray(s.data) for s in shards])
        return out

    def load_residual_device_stacks(self, stacks):
        """Rebuild per-device residual arrays from `residual_device_stacks`
        output (single-process meshes)."""
        import jax
        mesh = self.communicator.mesh
        if not stacks:
            return
        if mesh is None:
            raise ValueError(
                "checkpoint carries per-device sparse residuals but this "
                "DistOpt has no mesh; restore on the same topology")
        from jax.sharding import NamedSharding, PartitionSpec as P
        sh = NamedSharding(mesh, P())
        devs = sorted(mesh.devices.flatten(), key=lambda d: d.id)
        n_inner = len(self.opt.state_arrays())
        for idx, stacked in stacks.items():
            stacked = np.asarray(stacked)
            if stacked.shape[0] != len(devs):
                raise ValueError(
                    f"per-device residual saved on {stacked.shape[0]} "
                    f"devices cannot restore on a {len(devs)}-device "
                    "mesh (error-feedback state is per-device; use the "
                    "same topology)")
            arrs = [jax.device_put(stacked[i], d)
                    for i, d in enumerate(devs)]
            ga = jax.make_array_from_single_device_arrays(
                stacked.shape[1:], sh, arrs)
            i = int(idx) - n_inner
            if i < len(self._spars_order):
                self._spars_residual[self._spars_order[i]] = ga
            else:
                pend = getattr(self, "_pending_residuals", None)
                if pend is not None and i - len(self._spars_order) < \
                        len(pend):
                    pend[i - len(self._spars_order)] = ga

    def get_states(self):
        out = self.opt.get_states()
        for i, pid in enumerate(self._spars_order):
            out[f"spars_residual.{i}"] = np.asarray(self._spars_residual[pid])
        return out

    def set_states(self, states):
        self.opt.set_states(states)
        for i, pid in enumerate(self._spars_order):
            key = f"spars_residual.{i}"
            if key in states:
                self._spars_residual[pid] = jnp.asarray(states[key])
        # residuals restored BEFORE the first backward established the
        # param order (lazy creation): queue them; the sparse strategy
        # consumes them in creation order instead of starting from zeros,
        # keeping checkpoint-resume bit-identical
        n_known = len(self._spars_order)
        pending = []
        i = n_known
        while f"spars_residual.{i}" in states:
            pending.append(jnp.asarray(states[f"spars_residual.{i}"]))
            i += 1
        if pending:
            self._pending_residuals = pending

    def step(self):
        self.opt.step()

    def apply(self, param, grad):
        self.opt.apply(param, grad)

    # -- strategy 1: plain synchronous allreduce (ref opt.py:826) ----------
    def backward_and_update(self, loss: Tensor):
        t0 = time.perf_counter()
        col = _health_start(loss)
        n = 0
        with observe.span("opt.apply_updates"):
            for p, g in autograd.backward(loss):
                g.data = self.communicator.all_reduce(g.data) \
                    / self.world_size
                old = p.data if col is not None else None
                self.opt.apply(p, g)
                if col is not None:
                    col.observe(p, g.data, old, p.data)
                n += 1
        self.opt.step()
        observe.record_opt_update(n, time.perf_counter() - t0, "dense")

    def __call__(self, loss):
        return self.backward_and_update(loss)

    # -- strategy 2: reduced-precision allreduce (ref opt.py:867) ----------
    def backward_and_update_half(self, loss: Tensor, clipping=False,
                                 clip_value=100.0):
        """bf16 on TPU where the reference uses fp16 (ICI moves half the
        bytes; bf16 keeps fp32's exponent so no loss-scaling needed)."""
        t0 = time.perf_counter()
        col = _health_start(loss)
        n = 0
        with observe.span("opt.apply_updates"):
            for p, g in autograd.backward(loss):
                gd = g.data
                if clipping:
                    gd = jnp.clip(gd, -clip_value, clip_value)
                gd = self.communicator.all_reduce_half(gd) / self.world_size
                g.data = gd.astype(p.dtype)
                old = p.data if col is not None else None
                self.opt.apply(p, g)
                if col is not None:
                    col.observe(p, g.data, old, p.data)
                n += 1
        self.opt.step()
        observe.record_opt_update(n, time.perf_counter() - t0, "half")

    # -- strategy 3: async partial-parameter update (ref opt.py:922) -------
    def step_tag(self) -> int:
        """Rotating static partition index. Model compiles ONE executable
        per tag, each containing only that partition's collectives — the
        compiled-schedule analog of the reference's bandwidth rotation
        (XLA comm schedules are static, so a runtime mask could not skip
        the wire traffic)."""
        if not self._partial_mode:
            return 0
        tag = self._partial_counter % self.partial_k
        self._partial_counter += 1
        return tag

    def backward_and_partial_update(self, loss: Tensor, num_partitions=4):
        """Each step synchronizes only the params with index % k == sel;
        the rest update from local gradients (ref opt.py:922-992). In
        graph mode `sel` is the STATIC tag Model passed, so untouched
        partitions have no collective in the executable at all."""
        k = int(num_partitions)
        self.partial_k = k
        if not self._partial_mode:
            self._partial_mode = True
            # the in-flight trace is tag 0; the next invoke picks tag 1
            self._partial_counter = max(self._partial_counter, 1)
        sel = self._partial_static_idx
        if sel is None:  # eager path: rotate on the host counter
            sel = self._partial_counter % k
            self._partial_counter += 1
        t0 = time.perf_counter()
        col = _health_start(loss)
        n = 0
        with observe.span("opt.apply_updates"):
            for i, (p, g) in enumerate(autograd.backward(loss)):
                if i % k == sel:
                    g.data = self.communicator.all_reduce(g.data) \
                        / self.world_size
                old = p.data if col is not None else None
                self.opt.apply(p, g)
                if col is not None:
                    col.observe(p, g.data, old, p.data)
                n += 1
        self.opt.step()
        observe.record_opt_update(n, time.perf_counter() - t0, "partial")

    # -- strategy 4: sparsified allreduce w/ error feedback (ref :994) -----
    # -- low-level reference surface (ref opt.py:738-817) ------------------
    # The reference exposes the raw communicator verbs on DistOpt; here
    # each verb is a pure collective applied to the Tensor's backing array
    # (meaningful inside a mesh-mapped step; identity at world_size 1).

    def update(self, param, grad):
        """Single optimization step on one (param, grad); divides the
        allreduce-SUMMED gradient by world_size first, like the reference
        (opt.py:738-746) — pairs with `all_reduce`."""
        if self.world_size > 1:
            grad.data = grad.data / self.world_size
        self.apply(param, grad)

    def all_reduce(self, tensor):
        """In-place allreduce-sum of one Tensor (ref `synch`)."""
        tensor.data = self.communicator.all_reduce(tensor.data)

    def fused_all_reduce(self, tensors, send=True):
        """Allreduce a list of Tensors; buffer fusion is XLA's all-reduce
        combiner, so this is one psum per tensor that the compiler packs
        (ref `fusedSynch`). `send` kept for signature parity."""
        del send
        for t in tensors:
            t.data = self.communicator.all_reduce(t.data)

    def all_reduce_half(self, tensor):
        tensor.data = self.communicator.all_reduce_half(tensor.data)

    def fused_all_reduce_half(self, tensors, send=True):
        del send
        for t in tensors:
            t.data = self.communicator.all_reduce_half(t.data)

    def sparsification(self, tensor, accumulation, spars, topK):
        """Sparsified allreduce of one Tensor with optional error-feedback
        accumulation Tensor (ref opt.py:786 / communicator.cc:619-807)."""
        x = tensor.data if accumulation is None \
            else tensor.data + accumulation.data
        if topK:
            out, residual = self.communicator.sparse_all_reduce_topk(
                x, spars)
        else:
            out, residual = self.communicator.sparse_all_reduce_threshold(
                x, spars)
        if accumulation is not None:
            accumulation.data = residual
        tensor.data = out

    def fused_sparsification(self, tensors, accumulation, spars, topK):
        """Sparsified allreduce over a list of Tensors. `accumulation`
        must be a matching LIST of residual Tensors (or None) — the
        reference's single fused buffer has no analog here because there
        is no manual buffer packing (XLA fuses the collectives)."""
        if accumulation is not None and (
                not isinstance(accumulation, (list, tuple))
                or len(accumulation) != len(tensors)):
            # a hard raise, not assert: a single fused-buffer Tensor would
            # otherwise row-slice silently via Tensor.__getitem__
            raise TypeError(
                "accumulation must be a list of per-tensor residual "
                "Tensors matching `tensors` (no fused-buffer packing here)")
        for i, t in enumerate(tensors):
            acc = accumulation[i] if accumulation is not None else None
            self.sparsification(t, acc, spars, topK)

    def wait(self):
        """Stream fence (ref `wait`): no-op — XLA dataflow ordering
        subsumes the reference's cross-stream events."""
        self.communicator.wait()

    def backward_and_sparse_update(self, loss: Tensor, spars: float = 0.05,
                                   topK: bool = True, corr: bool = True):
        by_id = getattr(self.opt, "_params_by_id", {})
        has_sharded = any(getattr(p, "spec", None) is not None
                          for p in by_id.values())
        # precondition BEFORE any param is touched: per-leaf state specs
        # cannot grow mid-trace, so residuals on a sharded-param model
        # must have been pre-created at setup (raising mid-loop would
        # leave the model half-updated / leak tracers into opt state)
        if corr and has_sharded and any(
                getattr(p, "spec", None) is None
                and id(p) not in self._spars_residual
                for p in by_id.values()):
            raise RuntimeError(
                "error-feedback residuals on a model with sharded params "
                "must be pre-created: construct "
                "DistOpt(..., sparse_residuals=True)")
        t0 = time.perf_counter()
        col = _health_start(loss)
        n = 0
        with observe.span("opt.apply_updates"):
            for p, g in autograd.backward(loss):
                n += 1
                pid = id(p)
                old = p.data if col is not None else None
                if getattr(p, "spec", None) is not None:
                    # sharded param: its gradient is already a mesh shard
                    # — sparsifying per-shard indices across the data
                    # axis is well-defined, but the payoff is small (in
                    # TP/PP models the sharded tensors dominate FLOPs,
                    # not DP wire bytes) and the residual would have to
                    # shard too; take the dense reduction and keep
                    # sparsification for the replicated params.
                    g.data = self.communicator.all_reduce(g.data) \
                        / self.world_size
                    self.opt.apply(p, g)
                    if col is not None:
                        col.observe(p, g.data, old, p.data)
                    continue
                if corr and pid not in self._spars_residual:
                    pending = getattr(self, "_pending_residuals", None)
                    if pending:
                        # restored from a checkpoint before the order
                        # existed
                        self._spars_residual[pid] = pending.pop(0)
                    else:
                        self._spars_residual[pid] = jnp.zeros(
                            p.shape, dtype=p.dtype)
                    self._spars_order.append(pid)
                acc = self._spars_residual[pid] if corr else 0.0
                x = g.data + acc
                if topK:
                    out, residual = \
                        self.communicator.sparse_all_reduce_topk(x, spars)
                else:
                    out, residual = \
                        self.communicator.sparse_all_reduce_threshold(
                            x, spars)
                if corr:
                    self._spars_residual[pid] = residual
                g.data = out / self.world_size
                self.opt.apply(p, g)
                if col is not None:
                    col.observe(p, g.data, old, p.data)
        self.opt.step()
        observe.record_opt_update(n, time.perf_counter() - t0, "sparse")
