"""Multi-host cluster bootstrap — the reference's MPI/NCCL-id init, TPU-native.

Reference parity: `Communicator(nDev, buffSize)` does MPI_Init, derives
local rank from a hostname hash, broadcasts the NCCL unique id, and
ncclCommInitRank (src/io/communicator.cc:73-114); the multiprocess flavor
shares a pre-created NcclIdHolder (:54-70).

TPU-native redesign: `init()` wraps jax.distributed.initialize — the
coordinator address plays the NCCL-id role, process_id the MPI rank — and
`global_mesh()` builds a Mesh over ALL processes' devices so pjit/shard_map
collectives ride ICI within a host and DCN across hosts. On Cloud TPU pods
the three arguments are auto-detected from the TPU metadata server, so
`init()` with no arguments is the whole bootstrap.
"""

from __future__ import annotations

import os

import jax
import numpy as np

_initialized = False


def init(coordinator_address: str | None = None,
         num_processes: int | None = None,
         process_id: int | None = None,
         local_device_ids=None):
    """Join (or form) a multi-host JAX cluster.

    All arguments optional: on Cloud TPU they come from the environment;
    off-cloud, pass coordinator_address="host0:port", num_processes and
    process_id explicitly (the shape of the reference's MPI bootstrap,
    communicator.cc:73-103). Env fallbacks: SINGA_COORDINATOR,
    SINGA_NPROCS, SINGA_PROC_ID. Idempotent.
    """
    global _initialized
    if _initialized:
        return
    coordinator_address = coordinator_address or \
        os.environ.get("SINGA_COORDINATOR")
    if num_processes is None and "SINGA_NPROCS" in os.environ:
        num_processes = int(os.environ["SINGA_NPROCS"])
    if process_id is None and "SINGA_PROC_ID" in os.environ:
        process_id = int(os.environ["SINGA_PROC_ID"])
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids)
    _initialized = True


def shutdown():
    global _initialized
    if _initialized:
        jax.distributed.shutdown()
        _initialized = False


def process_index() -> int:
    """This process's rank (reference: MPIGlobalRank)."""
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def global_mesh(axis_sizes: dict | None = None):
    """Mesh over ALL hosts' devices (jax.devices() is global after init).

    Default: one 'data' axis over every chip in the slice. With axis_sizes,
    same contract as parallel.make_mesh but over global devices — put the
    fastest-varying (last) axis inside a host so its collectives stay on
    ICI and only the leading axes cross DCN.
    """
    from .parallel.mesh import make_mesh
    devs = jax.devices()
    if axis_sizes is None:
        axis_sizes = {"data": len(devs)}
    n = int(np.prod(list(axis_sizes.values())))
    assert n == len(devs), \
        f"mesh wants {n} devices, slice has {len(devs)}"
    return make_mesh(axis_sizes, devices=devs)


def topology() -> dict:
    """The live cluster topology: device/process counts plus this
    process's rank. `resilience.build_manifest` embeds it verbatim in
    each checkpoint manifest's `mesh` section (alongside the mesh
    `axes`); an elastic restart compares the saved copy against the
    live one to decide whether the restore reshards."""
    return {
        "n_devices": len(jax.devices()),
        "n_processes": jax.process_count(),
        "process_index": jax.process_index(),
    }


def host_label() -> str:
    """The bounded-cardinality `host=` metric label for THIS process:
    "host<process_index>" from the live `topology()`. Every `host=`
    label value in the package must originate here (or from topology()
    directly) — tools/check_metrics_names.py rule 6 rejects free-form
    host labels, the same enum-proof contract as reason=/bucket=.
    `SINGA_FLEET_HOST` overrides it for the MULTICHIP-style subprocess
    harnesses, where workers are separate OS processes that never ran
    jax.distributed.initialize (they would all report process 0)."""
    env = os.environ.get("SINGA_FLEET_HOST")
    if env:
        return env
    return f"host{topology()['process_index']}"


def resume_mesh(n: int | None = None, axis: str = "data"):
    """A data mesh over the devices THIS incarnation of the job has —
    the elastic-restart hook: a run killed on 8 workers relaunches on
    whatever survived, asks for `resume_mesh()`, and restores the
    checkpoint onto it (orbax reshards; see Model.load_checkpoint).
    `n` caps the device count (e.g. to match a power-of-two batch
    divisor); more devices than available is an error, fewer uses the
    first `n` (stable order, so every process picks the same set)."""
    from .parallel.mesh import make_mesh
    devs = jax.devices()
    if n is None:
        n = len(devs)
    if n > len(devs):
        raise ValueError(
            f"resume_mesh wants {n} devices, only {len(devs)} available")
    return make_mesh({axis: int(n)}, devices=devs[:int(n)])


def global_batch(host_array, mesh, axis: str = "data"):
    """Assemble a global jax.Array sharded along `axis` from a host array
    holding the FULL global batch (identical on every process). Each
    process materializes only its own devices' shards — the standard
    multi-host feeding pattern (reference analog: per-rank data partition,
    examples/cnn/train_cnn.py:58-72).
    """
    import jax.numpy as jnp  # noqa: F401 (kept lazy like the rest)
    from jax.sharding import NamedSharding, PartitionSpec as P

    n = mesh.shape[axis]
    assert host_array.shape[0] % n == 0, \
        f"axis '{axis}' has {n} shards; they must divide the global " \
        f"batch of {host_array.shape[0]}"
    sh = NamedSharding(mesh, P(axis))
    host = np.asarray(host_array)
    return jax.make_array_from_callback(host.shape, sh,
                                        lambda idx: host[idx])
