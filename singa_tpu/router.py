"""Serving control plane: the multi-replica router (ROADMAP item 5).

One `ServingEngine` is a single point of loss: its death takes every
in-flight and queued request with it. This module fronts N serving
REPLICAS — each a subprocess running its own engine, diag server and
fleet `ShardWriter` — behind one `Router` that owns the request's
fate end to end:

  - **Load balancing**: each dispatch picks the live replica with the
    lowest load score — the router's own in-flight count per replica
    plus the occupancy/queue-depth columns of that replica's fleet
    shard (the `fleet_serve` line `slo.fleet_serve_snapshot` publishes)
    when an aggregator over the shared spool is available.
  - **Admission control**: the router queue is BOUNDED (`queue_limit`);
    a submit over it is shed immediately as outcome "rejected", reason
    "shed" — bounded latency instead of an unbounded queue.
  - **Request failover**: the router keeps every routed request's
    prompt + sampling config (greedy, `max_new`) until a terminal
    outcome. A replica that misses its health deadline — watchdog-style
    calibrated liveness over its shard publish intervals
    (`watchdog.calibrated_deadline`) confirmed by a failed `/healthz`
    probe, or simply an exited process — is marked DEAD, and its
    in-flight and queued requests are resubmitted to surviving replicas
    with bounded decorrelated-jitter retries (resilience.py's backoff
    shape). Greedy decode is deterministic and every replica builds the
    byte-identical model (seeded init), so a retried request returns
    token-identical output: failover is invisible to the caller.
  - **Graceful drain**: `drain_replica()` stops routing to a replica,
    asks it to `ServingEngine.stop(drain=True)` — in-flight requests
    finish naturally, queued ones are handed BACK — and the router
    re-routes every handed-back request to the surviving replicas. A
    rolling restart loses nothing and produces no "evicted" terminals.

Request outcomes at the router are exactly `ROUTE_OUTCOMES`:
"completed" (tokens attached) or "rejected" (reason + detail) — never
silence. Replica states are exactly `REPLICA_STATES`: live / draining /
dead. Reasons on shed/failover/retry paths are exactly `ROUTE_REASONS`
(shed, replica_dead, drain, retry_exhausted) — all three tuples are the
enums tools/check_metrics_names.py rule 5 proves the `singa_route_*`
label values against.

CLI: `python -m singa_tpu.router --replica` runs one replica process
(engine + diag + shard writer + the HTTP control surface the router
drives); `--ab` is the kill-and-replace harness: 3 replicas under the
seeded Poisson workload from `bench_decode --serve`
(`serving.poisson_workload`), SIGKILL one mid-traffic, a standby
replica joins, and the run asserts ZERO lost requests (every submit
terminal, failover outputs token-identical to a clean arm) plus the
p99 TTFT delta through the event -> SERVE_rNN.json.
"""

from __future__ import annotations

import json
import os
import random
import subprocess
import sys
import tempfile
import threading
import time
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from . import observe

#: terminal outcomes a routed request can reach — "completed" with
#: tokens, or "rejected" with a reason; there is no third state, which
#: is the zero-loss contract (a lost request would be outcome None
#: forever, and the --ab harness fails on exactly that)
ROUTE_OUTCOMES = ("completed", "rejected")
OUTCOME_COMPLETED = "completed"
OUTCOME_REJECTED = "rejected"

#: why the router shed, failed over, or gave up — the low-cardinality
#: `reason=` label set on singa_route_* counters (lint rule 5; the
#: aliases below are literal re-statements, the form the lint's
#: constant-resolution proves membership from)
ROUTE_REASONS = ("shed", "replica_dead", "drain", "retry_exhausted")
REASON_SHED = "shed"
REASON_REPLICA_DEAD = "replica_dead"
REASON_DRAIN = "drain"
REASON_RETRY_EXHAUSTED = "retry_exhausted"

#: replica lifecycle at the router: live (routable), draining (finishing
#: in-flight, not routable), dead (failed or retired; never revived —
#: a replacement JOINS instead)
REPLICA_STATES = ("live", "draining", "dead")
STATE_LIVE = "live"
STATE_DRAINING = "draining"
STATE_DEAD = "dead"

#: engine-side rejection details that are worth retrying on another
#: replica (transient/local conditions); anything else (over-length
#: prompt, page budget) would fail identically everywhere and is
#: passed through to the caller as a terminal rejection
RETRYABLE_DETAILS = ("queue full", "not running", "draining")

#: the replica cold-start phases, in lifecycle order — the `phase=`
#: label on singa_replica_startup_seconds (lint rule 5). spawn =
#: fork-to-process-entry, import = the singa/jax stack, build = model
#: construction + engine start MINUS the XLA compile phases (trace/
#: lower/compile, introspect's compile-phase telemetry diffed across
#: the window), warm = bucket warmup minus ITS compile share, ready =
#: post-warm wiring (tracker/shard writer/diag/control surface) up to
#: the ready announcement
STARTUP_PHASES = ("spawn", "import", "build", "trace", "lower",
                  "compile", "warm", "ready")

#: synthetic tid for the startup-phase slices in the merged trace —
#: same far-above-real-idents convention as slo.QUEUE_TID
STARTUP_TID = 800_000

#: synthetic tids for the router's own trace track
ROUTER_QUEUE_TID = 910_000
ROUTER_DISPATCH_TID = 910_001


def _observe_startup(phase: str, seconds: float):
    """One cold-start phase duration into the startup histogram (the
    observatory's metric surface; the span ring carries the trace
    slices separately)."""
    assert phase in STARTUP_PHASES, phase
    observe.histogram(
        "singa_replica_startup_seconds",
        "replica cold-start wall seconds per startup phase "
        "(spawn/import/build/trace/lower/compile/warm/ready)").observe(
        max(0.0, float(seconds)), phase=phase)

_metrics_cache = None


def _metrics():
    # same memoize-with-revalidation shape as engine._metrics: cheap on
    # the per-request hot path, rebuilt after a registry reset
    global _metrics_cache
    c = _metrics_cache
    if c is not None and observe.get_registry().get(
            "singa_route_requests_total") is c["requests"]:
        return c
    _metrics_cache = c = {
        "requests": observe.counter(
            "singa_route_requests_total",
            "routed requests finished, by terminal outcome"),
        "rejects": observe.counter(
            "singa_route_rejects_total",
            "router-minted rejections by reason (shed at admission, "
            "retry budget exhausted, router drain)"),
        "failover": observe.counter(
            "singa_route_failover_total",
            "requests resubmitted away from a replica, by cause "
            "(replica death or graceful drain)"),
        "retries": observe.counter(
            "singa_route_retries_total",
            "re-dispatch attempts after the first, all causes"),
        "queue_depth": observe.gauge(
            "singa_route_queue_depth",
            "requests waiting in the router admission queue"),
        "replicas_live": observe.gauge(
            "singa_route_replicas_live",
            "replicas currently in the live state"),
        "replica_inflight": observe.gauge(
            "singa_route_replica_inflight",
            "requests dispatched to one replica and not yet terminal"),
        "request_s": observe.histogram(
            "singa_route_request_seconds",
            "router submit-to-terminal wall seconds per request"),
    }
    return c


def _http_json(url: str, payload=None, timeout: float = 10.0) -> dict:
    """One JSON round-trip (GET without payload, POST with)."""
    import urllib.request
    if payload is None:
        req = urllib.request.Request(url)
    else:
        req = urllib.request.Request(
            url, data=json.dumps(payload).encode("utf-8"),
            headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read().decode("utf-8"))


# ---- the routed request -----------------------------------------------------

class RouterRequest:
    """One request's router-side record: the prompt + sampling config
    are KEPT here until a terminal outcome, which is what makes
    failover possible at all — a dead replica takes nothing with it
    that the router cannot resubmit."""

    __slots__ = ("id", "prompt", "max_new", "submitted", "finished_ts",
                 "outcome", "reason", "detail", "tokens", "replica",
                 "attempts", "ttft_s", "events", "trace",
                 "replica_attr", "attr", "synthetic", "_done")

    def __init__(self, rid: int, prompt, max_new: int):
        self.id = rid
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        self.max_new = int(max_new)
        # perf_counter, NOT monotonic: these stamps feed the merged
        # trace, and perf_counter is the clock the fleet (epoch, perf)
        # handshake aligns across processes
        self.submitted = time.perf_counter()
        self.finished_ts = None
        self.outcome = None     # member of ROUTE_OUTCOMES when terminal
        self.reason = None      # member of ROUTE_REASONS when router-minted
        self.detail = None
        self.tokens: "list[int]" = []
        self.replica = None     # name of the replica that completed it
        self.attempts = 0
        self.ttft_s = None      # router-side: submit -> first token
        self.events: "list[tuple]" = []
        self.trace = None        # fleet-unique trace-context id
        self.replica_attr = None  # winning replica's LATENCY_ATTR split
        self.attr = None          # full route decomposition at terminal
        self.synthetic = False    # audit probe: excluded from RPS stamps
        self._done = threading.Event()

    def mark(self, event: str, **info):
        self.events.append((event, round(time.perf_counter(), 7), info))

    def done(self) -> bool:
        return self._done.is_set()

    def wait(self, timeout=None) -> bool:
        return self._done.wait(timeout)

    def result(self, timeout=None) -> "list[int]":
        if not self._done.wait(timeout):
            raise TimeoutError(f"request {self.id} not terminal")
        if self.outcome != OUTCOME_COMPLETED:
            raise RuntimeError(
                f"request {self.id} {self.outcome}: {self.detail}")
        return list(self.tokens)


class Replica:
    """Router-side record of one serving replica. `proc` is the
    subprocess when the router (or harness) spawned it — `None` for an
    externally managed or in-process (test stub) replica."""

    def __init__(self, name: str, ctl_url: str, *, host=None,
                 diag_url=None, proc=None):
        self.name = name
        self.ctl_url = ctl_url.rstrip("/")
        self.host = host or name
        self.diag_url = diag_url
        self.proc = proc
        self.state = STATE_LIVE
        self.state_detail = None
        self.inflight: "set[int]" = set()
        self.dispatched = 0
        self.completed = 0
        # dispatch/reject stamp rings — the /routerz admitted-RPS and
        # shed-rate columns (and the capacity model's demand signals)
        self.admit_times: "deque[float]" = deque(maxlen=1024)
        self.shed_times: "deque[float]" = deque(maxlen=1024)
        self.joined_ts = time.monotonic()
        # liveness calibration over shard publish intervals
        self.last_seq = None
        self.last_seq_change = None
        self.publish_intervals: "deque[float]" = deque(maxlen=256)
        self.liveness_deadline_s = None


# ---- the router -------------------------------------------------------------

class Router:
    """The control plane over N replicas (module docstring has the
    contract). All router threads are named `singa-route-*` (the
    conftest leak assert keys on the prefix)."""

    _seq = 0
    _seq_lock = threading.Lock()

    def __init__(self, fleet_dir=None, *, queue_limit=64,
                 max_attempts=6, retry_base_s=0.05, retry_max_s=2.0,
                 retry_total_s=120.0, retry_seed=None,
                 poll_wait_s=2.0, health_interval_s=0.1,
                 liveness_multiplier=10.0, liveness_floor_s=1.0,
                 liveness_ceiling_s=30.0, liveness_min_samples=5,
                 probe_timeout_s=2.0):
        from . import fleet
        self.fleet_dir = fleet_dir
        self.queue_limit = int(queue_limit)
        self.max_attempts = int(max_attempts)
        self.retry_base_s = float(retry_base_s)
        self.retry_max_s = float(retry_max_s)
        self.retry_total_s = float(retry_total_s)
        self.retry_seed = retry_seed
        self.poll_wait_s = float(poll_wait_s)
        self.health_interval_s = float(health_interval_s)
        self.liveness_multiplier = float(liveness_multiplier)
        self.liveness_floor_s = float(liveness_floor_s)
        self.liveness_ceiling_s = float(liveness_ceiling_s)
        self.liveness_min_samples = int(liveness_min_samples)
        self.probe_timeout_s = float(probe_timeout_s)
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queue: "deque[RouterRequest]" = deque()
        self._pending: "dict[int, RouterRequest]" = {}
        self._replicas: "dict[str, Replica]" = {}
        self._rid = 0
        self._rr = 0
        self._stop_evt = threading.Event()
        self._stopping = False
        self._threads: "list[threading.Thread]" = []
        self._senders: "list[threading.Thread]" = []
        self._terminal = {o: 0 for o in ROUTE_OUTCOMES}
        self._reasons = {r: 0 for r in ROUTE_REASONS}
        self._failovers = {REASON_REPLICA_DEAD: 0, REASON_DRAIN: 0}
        self._retries = 0
        # front-door stamp rings: accepted submits and queue-full
        # sheds — the router-level admitted-RPS / shed-rate the
        # capacity forecaster feeds on
        self._admit_times: "deque[float]" = deque(maxlen=4096)
        self._shed_times: "deque[float]" = deque(maxlen=4096)
        # finished routed-request timelines (trace id, hop events,
        # LATENCY_ATTR decomposition) — the /routerz?json=1 surface
        self._timelines: "deque[dict]" = deque(maxlen=256)
        # terminal-request listeners: (RouterRequest, timeline dict)
        # per terminal — the audit ShadowReplayer samples real
        # completed requests here (mirror of engine's listener list)
        self._request_listeners: "list" = []
        # balance on the installed aggregator when there is one (the
        # --ab coordinator installs it so /fleetz works too); otherwise
        # a private one over fleet_dir, polled from the health loop
        self._own_agg = None
        if fleet_dir is not None and fleet.get_aggregator() is None:
            self._own_agg = fleet.FleetAggregator(
                fleet_dir, stale_after_s=max(5.0, liveness_ceiling_s),
                poll_interval_s=min(0.25, health_interval_s))

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "Router":
        with Router._seq_lock:
            Router._seq += 1
            n = Router._seq
        for target, name in ((self._dispatch_loop, "dispatch"),
                             (self._health_loop, "health")):
            t = threading.Thread(target=target,
                                 name=f"singa-route-{name}-{n}",
                                 daemon=True)
            self._threads.append(t)
            t.start()
        install_router(self)
        self._export_gauges()
        return self

    def stop(self, timeout_s: float = 30.0):
        """Tear the router down: loops joined, every queued and pending
        request finished with a TERMINAL outcome (rejected, reason
        "drain" — never silence), replica subprocesses killed and
        reaped. Idempotent."""
        with self._lock:
            if self._stopping and not self._threads:
                return
            self._stopping = True
            self._stop_evt.set()
            self._cond.notify_all()
            leftover = list(self._queue)
            self._queue.clear()
        for req in leftover:
            self._finish(req, OUTCOME_REJECTED, reason=REASON_DRAIN,
                         detail="router stopped")
        deadline = time.monotonic() + float(timeout_s)
        for t in self._threads + self._senders:
            t.join(timeout=max(0.1, deadline - time.monotonic()))
        self._threads = []
        self._senders = []
        # any request a sender could not terminate in time still gets a
        # terminal outcome — zero-loss holds through shutdown too
        with self._lock:
            pending = list(self._pending.values())
        for req in pending:
            self._finish(req, OUTCOME_REJECTED, reason=REASON_DRAIN,
                         detail="router stopped")
        for rep in self.replicas():
            if rep.proc is not None and rep.proc.poll() is None:
                rep.proc.kill()
            if rep.proc is not None:
                try:
                    rep.proc.wait(timeout=10.0)
                except Exception:
                    pass
        if self._own_agg is not None:
            self._own_agg.stop_polling()
        if observe.is_enabled():
            m = _metrics()
            m["queue_depth"].set(0.0)
            m["replicas_live"].set(0.0)

    # -- replica registry --------------------------------------------------
    def add_replica(self, name: str, ctl_url: str, *, host=None,
                    diag_url=None, proc=None) -> Replica:
        rep = Replica(name, ctl_url, host=host, diag_url=diag_url,
                      proc=proc)
        with self._lock:
            if name in self._replicas:
                raise ValueError(f"replica {name!r} already registered")
            self._replicas[name] = rep
            self._cond.notify_all()
        self._export_gauges()
        return rep

    def replicas(self) -> "list[Replica]":
        with self._lock:
            return list(self._replicas.values())

    def get_replica(self, name: str) -> "Replica | None":
        with self._lock:
            return self._replicas.get(name)

    def mark_dead(self, rep: Replica, detail: str):
        """Flip a replica to DEAD (idempotent): no further dispatches
        go to it, waiting senders re-pick, and its process (if any) is
        killed and reaped so nothing leaks."""
        with self._lock:
            if rep.state == STATE_DEAD:
                return
            rep.state = STATE_DEAD
            rep.state_detail = detail
            self._cond.notify_all()
        if rep.proc is not None:
            if rep.proc.poll() is None:
                rep.proc.kill()
            try:
                rep.proc.wait(timeout=10.0)
            except Exception:
                pass
        if observe.is_enabled():
            observe.get_registry().emit({
                "kind": "route", "event": "replica_dead",
                "replica": rep.name, "detail": detail})
        self._export_gauges()

    def drain_replica(self, name: str, *, timeout_s: float = 120.0,
                      shutdown: bool = True) -> dict:
        """Graceful rolling-restart step for one replica: stop routing
        to it, ask its engine to finish in-flight work and hand queued
        requests back (`ServingEngine.stop(drain=True)`), wait for the
        router-side in-flight set to clear (the handed-back requests
        re-route themselves to surviving replicas), then optionally
        shut the replica process down. Returns the replica's drain
        response (handed_back ids etc.).

        Idempotent/re-entrant: a second call while the replica is
        already draining — or after it is dead — is a NO-OP returning
        {"noop": True, "state": ...}. The audit quarantine poll loop
        re-fires the same verdict until the episode clears, so the
        drain it drives must tolerate being asked twice."""
        rep = self.get_replica(name)
        if rep is None:
            raise ValueError(f"no replica {name!r}")
        with self._lock:
            if rep.state != STATE_LIVE:
                return {"noop": True, "replica": rep.name,
                        "state": rep.state}
            rep.state = STATE_DRAINING
            rep.state_detail = "drain requested"
        self._export_gauges()
        out = _http_json(rep.ctl_url + "/drain",
                         {"timeout_s": timeout_s},
                         timeout=timeout_s + 10.0)
        deadline = time.monotonic() + float(timeout_s)
        while time.monotonic() < deadline:
            with self._lock:
                if not rep.inflight:
                    break
            time.sleep(0.02)
        if shutdown:
            try:
                _http_json(rep.ctl_url + "/shutdown", {}, timeout=10.0)
            except Exception:
                pass
            if rep.proc is not None:
                try:
                    rep.proc.wait(timeout=30.0)
                except Exception:
                    rep.proc.kill()
                    rep.proc.wait(timeout=10.0)
            self.mark_dead(rep, "drained and retired")
        return out

    # -- submission --------------------------------------------------------
    def submit(self, prompt, max_new: int, *,
               synthetic: bool = False) -> RouterRequest:
        """Route one greedy request. Returns the handle immediately; a
        full router queue (or a stopped router) REJECTS it on the spot
        — reason "shed" / "drain" — instead of queueing unboundedly.
        `synthetic` marks an audit canary/replay probe: it rides the
        identical dispatch path (that is the point — a canary that
        skips the front door proves nothing) but never stamps the
        admit/shed RPS windows, so `/routerz` admitted-RPS and the
        capacity forecaster's arrival signal see only real demand."""
        with self._lock:
            self._rid += 1
            req = RouterRequest(self._rid, prompt, max_new)
            req.synthetic = bool(synthetic)
            # the fleet-unique trace context, minted at the front door:
            # pid-scoped so two routers (tests, a restart) never
            # collide, carried through every dispatch into the winning
            # replica's engine timeline
            req.trace = f"t{os.getpid():x}-{req.id}"
            if self._stopping:
                shed_reason, detail = REASON_DRAIN, "router stopped"
            elif len(self._queue) >= self.queue_limit:
                shed_reason = REASON_SHED
                detail = f"router queue full ({self.queue_limit})"
                if not req.synthetic:
                    self._shed_times.append(time.monotonic())
            else:
                shed_reason = None
                if not req.synthetic:
                    self._admit_times.append(time.monotonic())
                self._pending[req.id] = req
                self._queue.append(req)
                req.mark("queued", depth=len(self._queue))
                self._cond.notify_all()
                qd = len(self._queue)
        if shed_reason is not None:
            self._finish(req, OUTCOME_REJECTED, reason=shed_reason,
                         detail=detail)
        elif observe.is_enabled():
            _metrics()["queue_depth"].set(float(qd))
        return req

    # -- terminal bookkeeping ----------------------------------------------
    def _finish(self, req: RouterRequest, outcome: str, *, tokens=None,
                reason=None, detail=None, replica=None):
        assert outcome in ROUTE_OUTCOMES, outcome
        assert reason is None or reason in ROUTE_REASONS, reason
        from . import slo
        with self._lock:
            if req.outcome is not None:
                return
            req.outcome = outcome
            req.reason = reason
            req.detail = detail
            req.replica = replica
            if tokens is not None:
                req.tokens = [int(t) for t in tokens]
            req.finished_ts = time.perf_counter()
            req.mark("terminal", outcome=outcome, reason=reason)
            self._terminal[outcome] += 1
            if reason is not None:
                self._reasons[reason] += 1
            self._pending.pop(req.id, None)
        # the tail-latency decomposition: pure math over the hop marks
        # (+ the winning replica's own engine-side split), summing to
        # the request's total wall time — computed OUTSIDE the lock
        # (the request is terminal, its events are stable)
        req.attr = slo.attribute_route(
            req.submitted, req.finished_ts, list(req.events),
            replica_attr=req.replica_attr)
        total_s = round(req.finished_ts - req.submitted, 6)
        tlrec = {
            "id": req.id, "trace": req.trace, "outcome": outcome,
            "synthetic": bool(req.synthetic),
            "reason": reason, "detail": detail, "replica": replica,
            "attempts": req.attempts, "ttft_s": req.ttft_s,
            "submitted": round(req.submitted, 7),
            "finished": round(req.finished_ts, 7),
            "total_s": total_s, "attr": req.attr,
            "events": [(e, round(float(t), 7), i)
                       for e, t, i in list(req.events)],
        }
        with self._lock:
            self._timelines.append(tlrec)
        slo.note_attribution({"id": req.id, "outcome": outcome,
                              "trace": req.trace, "total_s": total_s,
                              "attr": req.attr})
        if observe.is_enabled():
            m = _metrics()
            m["requests"].inc(outcome=outcome)
            if reason is not None:
                m["rejects"].inc(reason=reason)
            m["request_s"].observe(req.finished_ts - req.submitted)
            observe.get_registry().emit({
                "kind": "route", "event": "terminal", "id": req.id,
                "outcome": outcome, "reason": reason,
                "replica": replica, "attempts": req.attempts,
                "detail": detail})
        for cb in tuple(self._request_listeners):
            try:
                cb(req, tlrec)
            except Exception:
                pass  # a listener must never break the routing path
        req._done.set()

    def add_request_listener(self, cb):
        """Register `cb(RouterRequest, timeline_dict)` called on every
        terminal routed request (after the timeline is booked, before
        the waiter wakes). Exceptions are swallowed."""
        if cb not in self._request_listeners:
            self._request_listeners.append(cb)

    def remove_request_listener(self, cb):
        if cb in self._request_listeners:
            self._request_listeners.remove(cb)

    # -- dispatch ----------------------------------------------------------
    def _dispatch_loop(self):
        while True:
            with self._lock:
                while not self._queue and not self._stopping:
                    self._cond.wait(timeout=0.1)
                if self._stopping:
                    return
                req = self._queue.popleft()
                qd = len(self._queue)
            if observe.is_enabled():
                _metrics()["queue_depth"].set(float(qd))
            t = threading.Thread(target=self._run_request, args=(req,),
                                 name=f"singa-route-req-{req.id}",
                                 daemon=True)
            with self._lock:
                self._senders.append(t)
                # reap finished sender threads so the list stays bounded
                self._senders = [s for s in self._senders if s.is_alive()
                                 or s is t]
            t.start()

    def _load_rows(self) -> dict:
        """host -> fleet rollup row, best effort (empty without an
        aggregator — balancing then rides the in-flight counts)."""
        from . import fleet
        agg = fleet.get_aggregator() or self._own_agg
        if agg is None:
            return {}
        try:
            agg.poll_if_due()
            roll = agg.rollup()
            return {r["host"]: r for r in roll["workers"]}
        except Exception:
            return {}

    def _score(self, rep: Replica, rows: dict) -> float:
        score = float(len(rep.inflight))
        row = rows.get(rep.host)
        serve = (row or {}).get("serve")
        if isinstance(serve, dict) and not (row or {}).get("stale"):
            score += float(serve.get("queue_depth") or 0)
            score += float(serve.get("occupancy") or 0)
        return score

    def _pick_replica(self, exclude=(), wait_until=None):
        """Lowest-load LIVE replica, preferring ones not in `exclude`
        (the replica that just failed). Blocks until `wait_until` for
        one to appear — a replacement may be joining — and returns None
        only when the wait budget is spent."""
        rows = self._load_rows()
        while True:
            with self._lock:
                live = [r for r in self._replicas.values()
                        if r.state == STATE_LIVE]
                cands = [r for r in live if r not in exclude] or live
                if cands:
                    self._rr += 1
                    lo = min(self._score(r, rows) for r in cands)
                    best = [r for r in cands
                            if self._score(r, rows) <= lo]
                    return best[self._rr % len(best)]
                if self._stopping or (
                        wait_until is not None
                        and time.monotonic() >= wait_until):
                    return None
                self._cond.wait(timeout=0.1)

    def _probe(self, rep: Replica) -> bool:
        try:
            out = _http_json(rep.ctl_url + "/healthz",
                             timeout=self.probe_timeout_s)
            return bool(out.get("ok"))
        except Exception:
            return False

    def _dispatch(self, rep: Replica, req: RouterRequest) -> dict:
        """Drive one attempt on one replica to a classifiable result:
        submit, then bounded /poll rounds until terminal. Every return
        is a dict with "outcome" plus "cause" for retryable failures
        ("transport", "requeued", "retryable_reject")."""
        payload = {"rid": req.id,
                   "prompt": [int(t) for t in req.prompt],
                   "max_new": req.max_new, "wait_s": self.poll_wait_s,
                   "trace": req.trace}
        if req.synthetic:
            payload["synthetic"] = True
        path = "/submit"
        # once a poll round returned "pending" the replica had ACCEPTED
        # the work (an engine request exists, tokens may be flowing) —
        # a later failure is a REPLAY of accepted work, not a dispatch
        # that never started; the tail attribution books the two
        # differently (failover_replay vs dispatch_retry)
        accepted = False
        while True:
            if self._stop_evt.is_set():
                return {"outcome": "error", "cause": "transport",
                        "detail": "router stopping",
                        "pending": accepted}
            if rep.state == STATE_DEAD:
                return {"outcome": "error", "cause": "transport",
                        "detail": "replica marked dead",
                        "pending": accepted}
            try:
                out = _http_json(rep.ctl_url + path, payload,
                                 timeout=self.poll_wait_s + 10.0)
            except Exception as e:
                return {"outcome": "error", "cause": "transport",
                        "detail": f"{type(e).__name__}: {e}",
                        "pending": accepted}
            st = out.get("outcome")
            if st == "pending":
                # bounded poll rounds keep every sender interruptible:
                # no thread ever blocks longer than one wait_s window
                path = "/submit"
                payload["resume"] = True
                accepted = True
                continue
            if st in ("requeued", "unknown"):
                return {"outcome": "error", "cause": "requeued",
                        "detail": "handed back by drain"
                        if st == "requeued"
                        else "replica lost request state",
                        "pending": accepted}
            if st == "rejected" and out.get("retryable"):
                return {"outcome": "error",
                        "cause": "retryable_reject",
                        "detail": out.get("detail"),
                        "pending": accepted}
            if st == "evicted":
                # the replica engine's crash path drained it — the
                # request is safe to resubmit (greedy determinism)
                return {"outcome": "error", "cause": "transport",
                        "detail": out.get("detail") or "evicted",
                        "pending": accepted}
            if st == "timeout":
                return {"outcome": "rejected", "retryable": False,
                        "detail": out.get("detail")
                        or "request deadline exceeded"}
            return out

    def _run_request(self, req: RouterRequest):
        rng = random.Random(
            None if self.retry_seed is None
            else (int(self.retry_seed) * 1_000_003 + req.id))
        t0 = time.monotonic()
        wait_until = t0 + self.retry_total_s
        prev_delay = self.retry_base_s
        last_rep = None
        while not self._stop_evt.is_set():
            elapsed = time.monotonic() - t0
            if req.attempts >= self.max_attempts \
                    or elapsed >= self.retry_total_s:
                return self._finish(
                    req, OUTCOME_REJECTED,
                    reason=REASON_RETRY_EXHAUSTED,
                    detail=f"{req.attempts} attempts over "
                           f"{elapsed:.1f}s")
            rep = self._pick_replica(
                exclude=(last_rep,) if last_rep is not None else (),
                wait_until=wait_until)
            if rep is None:
                if self._stop_evt.is_set():
                    break
                return self._finish(
                    req, OUTCOME_REJECTED,
                    reason=REASON_RETRY_EXHAUSTED,
                    detail="no live replica")
            req.attempts += 1
            if req.attempts > 1:
                self._retries += 1
                if observe.is_enabled():
                    _metrics()["retries"].inc()
            dispatch_ts = time.perf_counter()
            req.mark("dispatch", replica=rep.name,
                     attempt=req.attempts)
            with self._lock:
                rep.inflight.add(req.id)
                rep.dispatched += 1
                if not req.synthetic:
                    rep.admit_times.append(time.monotonic())
            self._export_gauges()
            try:
                out = self._dispatch(rep, req)
            finally:
                with self._lock:
                    rep.inflight.discard(req.id)
                self._export_gauges()
            st = out.get("outcome")
            if st == OUTCOME_COMPLETED:
                with self._lock:
                    rep.completed += 1
                if out.get("ttft_s") is not None:
                    # router-side TTFT: queue + failed attempts + the
                    # final replica's own submit->first-token time
                    req.ttft_s = (dispatch_ts - req.submitted
                                  + float(out["ttft_s"]))
                req.replica_attr = out.get("attr")
                return self._finish(req, OUTCOME_COMPLETED,
                                    tokens=out.get("tokens") or [],
                                    replica=rep.name)
            if st == OUTCOME_REJECTED and not out.get("retryable"):
                return self._finish(req, OUTCOME_REJECTED,
                                    detail=out.get("detail"),
                                    replica=rep.name)
            cause = out.get("cause")
            probe_s = 0.0
            if cause == "transport":
                # SIGKILL shows up here first (connection reset long
                # before the shard goes stale): confirm with a probe so
                # failover is prompt, not a liveness-deadline later
                if rep.state == STATE_LIVE:
                    p0 = time.perf_counter()
                    alive = self._probe(rep)
                    probe_s = time.perf_counter() - p0
                    if not alive:
                        self.mark_dead(
                            rep,
                            f"dispatch failed ({out.get('detail')}) "
                            "and /healthz probe failed")
            if cause == "retryable_reject":
                # the replica turned the request away at ITS front
                # door (queue full / draining): that is the per-
                # replica shed signal the capacity table surfaces
                if not req.synthetic:
                    with self._lock:
                        rep.shed_times.append(time.monotonic())
            req.mark("failover", replica=rep.name, cause=cause,
                     detail=out.get("detail"),
                     probe_s=round(probe_s, 7),
                     pending=bool(out.get("pending")))
            if cause == "transport":
                if rep.state == STATE_DEAD:
                    with self._lock:
                        self._failovers[REASON_REPLICA_DEAD] += 1
                    if observe.is_enabled():
                        _metrics()["failover"].inc(
                            reason=REASON_REPLICA_DEAD)
            elif cause == "requeued":
                fo = REASON_DRAIN if rep.state == STATE_DRAINING \
                    else REASON_REPLICA_DEAD
                with self._lock:
                    self._failovers[fo] += 1
                if observe.is_enabled():
                    if fo == REASON_DRAIN:
                        _metrics()["failover"].inc(reason=REASON_DRAIN)
                    else:
                        _metrics()["failover"].inc(
                            reason=REASON_REPLICA_DEAD)
            last_rep = rep
            delay = min(rng.uniform(self.retry_base_s,
                                    max(self.retry_base_s,
                                        prev_delay * 3.0)),
                        self.retry_max_s)
            prev_delay = delay
            self._stop_evt.wait(delay)
        self._finish(req, OUTCOME_REJECTED, reason=REASON_DRAIN,
                     detail="router stopped")

    # -- health ------------------------------------------------------------
    def _health_loop(self):
        from . import watchdog
        while not self._stop_evt.wait(self.health_interval_s):
            rows = self._load_rows()
            now = time.monotonic()
            for rep in self.replicas():
                if rep.state == STATE_DEAD:
                    continue
                if rep.proc is not None and rep.proc.poll() is not None:
                    self.mark_dead(
                        rep, "process exited "
                             f"rc={rep.proc.returncode}")
                    continue
                row = rows.get(rep.host)
                if row is None:
                    continue
                seq = row.get("seq")
                if seq != rep.last_seq:
                    if rep.last_seq is not None \
                            and rep.last_seq_change is not None:
                        rep.publish_intervals.append(
                            now - rep.last_seq_change)
                    rep.last_seq = seq
                    rep.last_seq_change = now
                    continue
                # watchdog-style calibrated liveness: armed only after
                # enough publish intervals establish "normal", then a
                # shard older than clamp(p99 x multiplier, floor,
                # ceiling) makes the replica a SUSPECT — confirmed dead
                # only when the /healthz probe fails too (a slow
                # publisher with a live control surface keeps serving)
                dl = watchdog.calibrated_deadline(
                    rep.publish_intervals,
                    multiplier=self.liveness_multiplier,
                    floor_s=self.liveness_floor_s,
                    ceiling_s=self.liveness_ceiling_s,
                    min_samples=self.liveness_min_samples)
                rep.liveness_deadline_s = dl
                if dl is not None and rep.last_seq_change is not None \
                        and now - rep.last_seq_change > dl \
                        and not self._probe(rep):
                    self.mark_dead(
                        rep, f"shard age "
                             f"{now - rep.last_seq_change:.2f}s > "
                             f"liveness deadline {dl:.2f}s and "
                             "/healthz probe failed")

    # -- introspection -----------------------------------------------------
    def _export_gauges(self):
        if not observe.is_enabled():
            return
        m = _metrics()
        with self._lock:
            reps = list(self._replicas.values())
            qd = len(self._queue)
        live = 0
        for rep in reps:
            assert rep.state in REPLICA_STATES, rep.state
            if rep.state == STATE_LIVE:
                live += 1
            m["replica_inflight"].set(float(len(rep.inflight)),
                                      replica=rep.name)
        m["replicas_live"].set(float(live))
        m["queue_depth"].set(float(qd))

    def request_timelines(self) -> "list[dict]":
        """Locked copy of the bounded terminal-request timeline ring
        (newest last). Diag threads read this while the dispatch loop
        appends — the copy-under-lock keeps them from racing."""
        with self._lock:
            return [dict(t) for t in self._timelines]

    @staticmethod
    def _rate(stamps: "deque[float]", window_s: float) -> float:
        """Events/second over the trailing window of a monotonic stamp
        ring, with the engine.rps short-span correction (a full ring
        younger than the window covers less than `window_s`)."""
        now = time.monotonic()
        n = sum(1 for t in stamps if now - t <= window_s)
        span = window_s
        if stamps and len(stamps) == stamps.maxlen \
                and now - stamps[0] < window_s:
            span = max(now - stamps[0], 1e-6)
        return n / span

    def admit_rate(self, window_s: float = 10.0) -> float:
        """Requests/second accepted at the front door over the
        trailing window — the demand forecaster's arrival signal."""
        with self._lock:
            return self._rate(self._admit_times, window_s)

    def shed_rate(self, window_s: float = 10.0) -> float:
        """Requests/second shed at the front door (queue full) over
        the trailing window."""
        with self._lock:
            return self._rate(self._shed_times, window_s)

    def snapshot(self) -> dict:
        with self._lock:
            reps = []
            for rep in self._replicas.values():
                reps.append({
                    "name": rep.name, "state": rep.state,
                    "state_detail": rep.state_detail,
                    "host": rep.host,
                    "inflight": len(rep.inflight),
                    "dispatched": rep.dispatched,
                    "completed": rep.completed,
                    "admitted_rps": round(
                        self._rate(rep.admit_times, 10.0), 3),
                    "shed_rate": round(
                        self._rate(rep.shed_times, 10.0), 3),
                    "liveness_deadline_s": rep.liveness_deadline_s,
                })
            return {
                "queue_depth": len(self._queue),
                "queue_limit": self.queue_limit,
                "pending": len(self._pending),
                "terminal": dict(self._terminal),
                "reasons": dict(self._reasons),
                "failovers": dict(self._failovers),
                "retries": self._retries,
                "admitted_rps": round(
                    self._rate(self._admit_times, 10.0), 3),
                "shed_rate": round(
                    self._rate(self._shed_times, 10.0), 3),
                "replicas": reps,
            }


# ---- module singleton -------------------------------------------------------

_router: "Router | None" = None
_registry_lock = threading.Lock()


def install_router(router: Router) -> Router:
    global _router
    with _registry_lock:
        _router = router
    return router


def get_router() -> "Router | None":
    return _router


def reset():
    """Stop and drop the process router (conftest contract: router
    threads joined, replica subprocesses reaped, pending requests
    drained with a terminal outcome)."""
    global _router
    with _registry_lock:
        r = _router
        _router = None
    if r is not None:
        r.stop()


# ---- report surfaces --------------------------------------------------------

def serving_lines() -> "list[str]":
    """Router rows for /statusz's `== serving ==` section (empty
    without an installed router)."""
    r = get_router()
    if r is None:
        return []
    s = r.snapshot()
    by_state = {st: 0 for st in REPLICA_STATES}
    for rep in s["replicas"]:
        by_state[rep["state"]] += 1
    t, reasons = s["terminal"], s["reasons"]
    lines = [
        f"router: replicas {by_state['live']} live / "
        f"{by_state['draining']} draining / {by_state['dead']} dead, "
        f"queue {s['queue_depth']}/{s['queue_limit']} "
        f"(pending {s['pending']})",
        f"  routed: completed {t['completed']}, rejected "
        f"{t['rejected']} (shed {reasons['shed']}, retry_exhausted "
        f"{reasons['retry_exhausted']}, drain {reasons['drain']}), "
        f"retries {s['retries']}, failover replica_dead "
        f"{s['failovers']['replica_dead']} / drain "
        f"{s['failovers']['drain']}",
    ]
    for rep in s["replicas"]:
        dl = rep["liveness_deadline_s"]
        lines.append(
            f"  replica {rep['name']}: {rep['state']}, inflight "
            f"{rep['inflight']}, dispatched {rep['dispatched']}, "
            f"completed {rep['completed']}, liveness deadline "
            + (f"{dl:.2f}s" if dl is not None else "uncalibrated")
            + (f" ({rep['state_detail']})"
               if rep["state_detail"] else ""))
    return lines


def fleetz_lines() -> "list[str]":
    """Router section for /fleetz (empty without an installed
    router): per-replica state plus the shed/failover/retry counters —
    the control-plane view next to the data-plane serving table."""
    r = get_router()
    if r is None:
        return []
    s = r.snapshot()
    t, reasons = s["terminal"], s["reasons"]
    lines = [
        "== router ==",
        f"queue {s['queue_depth']}/{s['queue_limit']}   completed "
        f"{t['completed']}   rejected {t['rejected']}   shed "
        f"{reasons['shed']}   failover(replica_dead) "
        f"{s['failovers']['replica_dead']}   failover(drain) "
        f"{s['failovers']['drain']}   retry_exhausted "
        f"{reasons['retry_exhausted']}   retries {s['retries']}   "
        f"admitted {s['admitted_rps']:.2f}/s   shed "
        f"{s['shed_rate']:.2f}/s",
        f"{'replica':<12} {'state':>9} {'inflight':>9} "
        f"{'dispatched':>11} {'completed':>10} {'admit/s':>8} "
        f"{'shed/s':>7} deadline",
    ]
    for rep in s["replicas"]:
        dl = rep["liveness_deadline_s"]
        lines.append(
            f"{rep['name']:<12} {rep['state']:>9} "
            f"{rep['inflight']:>9} {rep['dispatched']:>11} "
            f"{rep['completed']:>10} {rep['admitted_rps']:>8.2f} "
            f"{rep['shed_rate']:>7.2f} "
            + (f"{dl:.2f}s" if dl is not None else "uncalibrated"))
    return lines


def router_report() -> str:
    """Text block for /routerz: the fleetz table plus a bounded tail
    of recent terminal requests (id / outcome / hops / wall / top
    latency bucket) read via the locked timeline copy."""
    lines = fleetz_lines()
    if not lines:
        return ("no Router installed "
                "(singa_tpu.router.Router(...).start())")
    r = get_router()
    recent = r.request_timelines()[-8:] if r is not None else []
    if recent:
        lines.append("recent requests:")
        for tl in recent:
            attr = tl.get("attr") or {}
            top = max(attr.items(), key=lambda kv: kv[1],
                      default=(None, 0.0))
            where = tl.get("replica") or tl.get("reason") or "-"
            lines.append(
                f"  req {tl['id']} [{tl.get('trace')}] "
                f"{tl['outcome']} via {where}, "
                f"{tl['attempts']} attempt(s), "
                f"{tl['total_s']:.4f}s"
                + (f", top {top[0]} {top[1]:.4f}s"
                   if top[0] is not None else ""))
    return "\n".join(lines)


def router_json() -> dict:
    """JSON body for /routerz?json=1: the snapshot plus a bounded tail
    of terminal request timelines (trace id, hop marks, attribution)."""
    r = get_router()
    if r is None:
        return {"installed": False}
    return {"installed": True, "snapshot": r.snapshot(),
            "requests": r.request_timelines()[-64:]}


def router_trace_events() -> "list[dict]":
    """Chrome-trace events for the router's own track in the merged
    fleet trace: a synthetic "router" process (sorted above the
    replicas) with a queue thread and a dispatch thread, one X slice
    per request's queue wait, one per dispatch hop, and the trace_ctx
    flow "s"/"f" endpoints that stitch each request to the winning
    replica's engine slices. Perf-counter stamps map to wall time via
    this process's own clock offset — the same pairing the replica
    shard headers use, so the tracks align."""
    r = get_router()
    if r is None:
        return []
    from .slo import TRACE_CTX_CAT
    pid = os.getpid()
    off = time.time() - time.perf_counter()

    def us(t_perf):
        return (float(t_perf) + off) * 1e6

    events: "list[dict]" = [
        {"ph": "M", "name": "process_name", "pid": pid,
         "args": {"name": f"router (pid {pid})"}},
        {"ph": "M", "name": "process_sort_index", "pid": pid,
         "args": {"sort_index": -1}},
        {"ph": "M", "name": "thread_name", "pid": pid,
         "tid": ROUTER_QUEUE_TID, "args": {"name": "router queue"}},
        {"ph": "M", "name": "thread_name", "pid": pid,
         "tid": ROUTER_DISPATCH_TID,
         "args": {"name": "router dispatch"}},
    ]
    for tl in r.request_timelines():
        rid = tl["id"]
        sub = float(tl["submitted"])
        fin = float(tl["finished"])
        evs = [(e, float(t), i) for e, t, i in tl.get("events") or []]
        dispatches = [(t, i) for e, t, i in evs if e == "dispatch"]
        failovers = [(t, i) for e, t, i in evs if e == "failover"]
        q_end = dispatches[0][0] if dispatches else fin
        events.append({
            "ph": "X", "cat": "route", "name": f"req {rid} queued",
            "ts": us(sub), "dur": max(0.0, (q_end - sub) * 1e6),
            "pid": pid, "tid": ROUTER_QUEUE_TID,
            "args": {"trace": tl.get("trace"),
                     "outcome": tl["outcome"],
                     "reason": tl.get("reason")}})
        for k, (t_d, info) in enumerate(dispatches):
            end = dispatches[k + 1][0] if k + 1 < len(dispatches) \
                else fin
            args = {"trace": tl.get("trace"),
                    "replica": info.get("replica"),
                    "attempt": info.get("attempt")}
            if k < len(failovers):
                args["cause"] = failovers[k][1].get("cause")
            else:
                args["outcome"] = tl["outcome"]
                args["reason"] = tl.get("reason")
            events.append({
                "ph": "X", "cat": "route",
                "name": f"req {rid} hop {k + 1} -> "
                        f"{info.get('replica')}",
                "ts": us(t_d), "dur": max(0.0, (end - t_d) * 1e6),
                "pid": pid, "tid": ROUTER_DISPATCH_TID, "args": args})
        if dispatches and tl.get("trace") and fin > q_end:
            # flow start just inside the first hop slice, finish just
            # inside the last hop slice: the winning replica's binding
            # step (admitted AFTER dispatch, bound BEFORE the router
            # saw the terminal outcome) lands strictly between them
            eps = min(1e-6, (fin - q_end) / 4.0)
            events.append({
                "ph": "s", "cat": TRACE_CTX_CAT, "name": "trace",
                "id": str(tl["trace"]), "ts": us(q_end + eps),
                "pid": pid, "tid": ROUTER_DISPATCH_TID})
            events.append({
                "ph": "f", "cat": TRACE_CTX_CAT, "name": "trace",
                "id": str(tl["trace"]), "bp": "e",
                "ts": us(fin - eps),
                "pid": pid, "tid": ROUTER_DISPATCH_TID})
    return events


# ---- the replica process ----------------------------------------------------

class ReplicaControl:
    """The HTTP control surface a replica exposes to the router (and to
    in-process test stubs): /submit with bounded waits, /healthz,
    /drain (graceful engine stop, handed-back ids reported), and
    /shutdown. Threads are daemonized and the server thread is named
    `singa-route-ctl-<port>` so the conftest leak assert covers it."""

    def __init__(self, eng, host="127.0.0.1", port=0):
        self.eng = eng
        self.draining = False
        self._reqs: "dict[int, object]" = {}  # rid -> EngineRequest
        self._handed: "set[int]" = set()
        self._lock = threading.Lock()
        self.shutdown_evt = threading.Event()
        ctl = self

        class _CtlHandler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # noqa: A002
                pass

            def _reply(self, obj, status=200):
                body = json.dumps(obj).encode("utf-8")
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802
                if self.path.rstrip("/") == "/healthz":
                    self._reply({"ok": True, "pid": os.getpid(),
                                 "draining": ctl.draining})
                else:
                    self._reply({"error": f"no endpoint {self.path}"},
                                status=404)

            def do_POST(self):  # noqa: N802
                n = int(self.headers.get("Content-Length") or 0)
                try:
                    body = json.loads(self.rfile.read(n) or b"{}")
                except ValueError:
                    self._reply({"error": "bad json"}, status=400)
                    return
                path = self.path.rstrip("/")
                try:
                    if path == "/submit":
                        self._reply(ctl.handle_submit(body))
                    elif path == "/drain":
                        self._reply(ctl.handle_drain(body))
                    elif path == "/shutdown":
                        ctl.shutdown_evt.set()
                        self._reply({"ok": True})
                    else:
                        self._reply(
                            {"error": f"no endpoint {self.path}"},
                            status=404)
                except Exception as e:  # surface, don't kill the thread
                    self._reply({"error":
                                 f"{type(e).__name__}: {e}"},
                                status=500)

        self.httpd = ThreadingHTTPServer((host, int(port)), _CtlHandler)
        self.httpd.daemon_threads = True
        self.port = self.httpd.server_address[1]
        self.url = f"http://{host}:{self.port}"
        self._thread = threading.Thread(
            target=self.httpd.serve_forever,
            kwargs={"poll_interval": 0.1},
            name=f"singa-route-ctl-{self.port}", daemon=True)
        self._thread.start()

    # -- handlers ----------------------------------------------------------
    def handle_submit(self, body: dict) -> dict:
        rid = int(body["rid"])
        wait_s = float(body.get("wait_s", 2.0))
        with self._lock:
            req = self._reqs.get(rid)
        if req is None:
            if self.draining:
                return {"outcome": "rejected", "retryable": True,
                        "detail": "replica draining"}
            try:
                req = self.eng.submit(
                    np.asarray(body["prompt"], np.int32),
                    int(body["max_new"]),
                    trace_ctx=body.get("trace"),
                    synthetic=bool(body.get("synthetic")))
            except TypeError:
                # test stubs model a 2-arg submit; the trace id and
                # synthetic tag are merely lost, not load-bearing
                req = self.eng.submit(
                    np.asarray(body["prompt"], np.int32),
                    int(body["max_new"]))
            with self._lock:
                self._reqs[rid] = req
            # push the in-flight timeline to disk NOW: if the router
            # SIGKILLs this replica mid-request, the merged trace still
            # shows the victim's partial track (shard files outlive
            # the process)
            try:
                from . import fleet
                w = fleet.get_shard_writer()
                if w is not None:
                    w.publish()
            except Exception:
                pass
        deadline = time.monotonic() + wait_s
        while req.outcome is None and time.monotonic() < deadline:
            with self._lock:
                if rid in self._handed:
                    # drained out of the queue before admission: hand
                    # it back to the router (it re-routes; the rid is
                    # forgotten so a forced same-replica resubmit makes
                    # a FRESH engine request)
                    self._handed.discard(rid)
                    self._reqs.pop(rid, None)
                    return {"outcome": "requeued"}
            req.wait(timeout=0.05)
        if req.outcome is None:
            return {"outcome": "pending"}
        with self._lock:
            self._reqs.pop(rid, None)
            self._handed.discard(rid)
        out = {"outcome": req.outcome, "detail": req.detail}
        if req.outcome == "completed":
            out["tokens"] = [int(t) for t in req.tokens]
            out["ttft_s"] = req.ttft_s
            try:
                from . import slo
                evs = list(getattr(req, "events", []) or [])
                if evs:
                    out["attr"] = slo.attribute_timeline(
                        {"events": evs})
            except Exception:
                pass
        elif req.outcome == "rejected":
            out["retryable"] = any(
                s in (req.detail or "") for s in RETRYABLE_DETAILS)
        return out

    def handle_drain(self, body: dict) -> dict:
        self.draining = True
        handed = self.eng.stop(
            drain=True,
            drain_timeout_s=float(body.get("timeout_s", 120.0)))
        handed_ids = {id(r) for r in handed}
        with self._lock:
            ids = [rid for rid, r in self._reqs.items()
                   if id(r) in handed_ids]
            self._handed.update(ids)
        return {"ok": True, "handed_back": sorted(ids),
                "drained": len(handed)}

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()
        self._thread.join(timeout=5.0)


def _build_replica_model(vocab: int, dim: int, layers: int,
                         max_seq: int):
    """Deterministic serving model: every replica builds THIS — same
    architecture, same seeded init (device.py's default key(0) RNG) —
    so greedy decode is token-identical across replicas and failover
    resubmission is invisible to the caller."""
    from . import device, models, tensor
    dev = device.best_device()
    m = models.create_model("gpt", vocab_size=vocab, max_seq=max_seq,
                            dim=dim, num_heads=4, num_layers=layers)
    rng0 = np.random.RandomState(0)
    ids = tensor.from_numpy(
        rng0.randint(0, vocab, (2, 8)).astype(np.int32), device=dev)
    m.compile([ids], is_train=False, use_graph=False)
    m.eval()
    return m


def _replica_main(args) -> int:
    """One serving replica: engine + fleet shard writer + diag server +
    the control surface, announced on stdout as a JSON "ready" line.

    The cold-start observatory stamps every startup phase
    (STARTUP_PHASES: spawn -> import -> build -> trace -> lower ->
    compile -> warm -> ready) into `singa_replica_startup_seconds`,
    notes a span per phase on the STARTUP_TID track (the merged fleet
    trace renders them as a "startup" thread), and reports the
    breakdown — plus spawn-to-first-token — in the ready line. The
    trace/lower/compile splits come from diffing introspect's
    `compile_phase_totals()` around the build and warm windows, so
    build/warm report pure non-XLA wall time."""
    t_entry = time.time()
    t0 = time.time()
    from . import diag, engine, fleet, introspect, resilience, slo, \
        warmstart
    startup = {"import": time.time() - t0}
    spawned_at = getattr(args, "spawned_at", None)
    if spawned_at is not None:
        startup["spawn"] = max(0.0, t_entry - float(spawned_at))
    observe.enable(True)
    observe.enable_span_records()
    # warm store BEFORE any staged build: with --warm-dir every
    # executable this replica compiles lands in (or loads from) the
    # shared store, so a restart — watchdog, resilience, or scale-up —
    # re-stages from disk instead of re-compiling
    if getattr(args, "warm_dir", None):
        warmstart.enable(args.warm_dir)
    else:
        warmstart.maybe_enable_from_env()
    T = args.prompt_hi + args.new_hi
    c0 = introspect.compile_phase_totals()
    t0 = time.time()
    m = _build_replica_model(args.vocab, args.dim, args.layers, T)
    eng = engine.ServingEngine(
        m, max_slots=args.slots, page_size=args.page_size, max_ctx=T,
        queue_limit=max(128, 8 * args.slots),
        steps_per_sync=2).start()
    build_wall = time.time() - t0
    c1 = introspect.compile_phase_totals()
    # warm every prompt bucket the workload can hit (plus the decode
    # executable) BEFORE announcing ready: the router's p99 TTFT must
    # measure serving, not XLA compiles
    t0 = time.time()
    _, first_token_wall = eng.prewarm((args.prompt_lo, args.prompt_hi))
    warm_wall = time.time() - t0
    c2 = introspect.compile_phase_totals()
    build_xla = sum(max(0.0, c1[p] - c0[p])
                    for p in introspect.COMPILE_PHASES)
    warm_xla = sum(max(0.0, c2[p] - c1[p])
                   for p in introspect.COMPILE_PHASES)
    for p in introspect.COMPILE_PHASES:
        startup[p] = max(0.0, c2[p] - c0[p])
    startup["build"] = max(0.0, build_wall - build_xla)
    startup["warm"] = max(0.0, warm_wall - warm_xla)
    t0 = time.time()
    tracker = slo.SLOTracker(slo.SLOConfig(), capacity=8192).install()
    assert tracker is not None
    slo.install_tail()
    plan = None
    if getattr(args, "fault_delay", 0.0):
        # the --ab fault arm: a fixed per-engine-step stall makes
        # decode the provably dominant tail bucket on /tailz
        plan = resilience.FaultPlan().delay(
            "serving.engine_step", float(args.fault_delay),
            times=10 ** 9)
    if getattr(args, "corrupt_after", 0):
        # the audit --ab corrupt arm: the Nth fingerprint tick's
        # fault_point("audit.corrupt_params") bit-flips one layer of
        # THIS replica's params (audit.ParamFingerprinter._corrupt) —
        # the silent-data-corruption stand-in the observatory must
        # catch from the outside
        plan = (plan or resilience.FaultPlan()).fail(
            "audit.corrupt_params", nth=int(args.corrupt_after))
    if plan is not None:
        resilience.install_fault_plan(plan)
    # the correctness observatory's replica half: the startup
    # fingerprint plus the low-rate recompute timer whose snapshot
    # rides the fleet_audit shard line (started before the shard
    # writer so the first publish already carries a fingerprint)
    from . import audit
    audit.install_fingerprint(
        m, eng,
        interval_s=float(getattr(args, "audit_interval", 0.25)))
    fleet.start_shard_writer(args.fleet_dir,
                             interval_s=args.publish_interval)
    dsrv = diag.start_diag_server(port=0)
    ctl = ReplicaControl(eng)
    startup["ready"] = time.time() - t0
    for p in STARTUP_PHASES:
        if p in startup:
            _observe_startup(p, startup[p])
    # the startup track: phases laid out back-to-back from the spawn
    # stamp on a dedicated tid (real wall placement would overlap —
    # compile time is interleaved with build/warm — so the track reads
    # as a clean waterfall whose slices sum to the startup wall)
    off = time.time() - time.perf_counter()
    cursor = (float(spawned_at) if spawned_at is not None
              else t_entry - startup["import"]) - off
    for p in STARTUP_PHASES:
        dur = startup.get(p)
        if not dur:
            continue
        observe.note_span(f"startup.{p}", cursor, dur,
                          kind="startup", tid=STARTUP_TID)
        cursor += dur
    ready = {
        "event": "ready", "name": args.name, "pid": os.getpid(),
        "ctl_port": ctl.port, "diag_port": dsrv.port,
        "startup": {p: round(startup[p], 6) for p in STARTUP_PHASES
                    if p in startup}}
    if spawned_at is not None and first_token_wall is not None:
        ready["spawn_to_first_token_s"] = round(
            first_token_wall - float(spawned_at), 6)
    if warmstart.is_enabled():
        # the parent's warm A/B reads these to prove the child really
        # loaded from the store (hits) vs compiled fresh (misses)
        ws = warmstart.snapshot()
        ready["warm"] = {
            "root": ws["root"], "lookups": ws["lookups"],
            "hit_rate": ws["hit_rate"], "exports": ws["exports"],
            "entries": ws["entries"]}
    print(json.dumps(ready), flush=True)
    try:
        while not ctl.shutdown_evt.wait(0.2):
            pass
    except KeyboardInterrupt:
        pass
    ctl.stop()
    eng.stop()
    audit.reset()
    fleet.uninstall()
    diag.stop_diag_server()
    resilience.clear_fault_plan()
    slo.reset()
    print(json.dumps({"event": "exit", "name": args.name, "ok": True}),
          flush=True)
    return 0


# ---- spawn + handshake ------------------------------------------------------

def spawn_replica(name: str, fleet_dir: str, args, *,
                  ready_timeout_s: float = 900.0):
    """Spawn `python -m singa_tpu.router --replica` and wait for its
    "ready" line. Returns (proc, ready_dict). The child's stdout keeps
    flowing to OUR stderr afterwards via a daemon reader thread (named
    singa-route-io-*; it exits on child EOF)."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu", SINGA_FLEET_HOST=name)
    env.pop("SINGA_TPU_DIAG_PORT", None)
    cmd = [sys.executable, "-m", "singa_tpu.router", "--replica",
           "--name", name, "--fleet-dir", fleet_dir,
           "--vocab", str(args.vocab), "--dim", str(args.dim),
           "--layers", str(args.layers),
           "--prompt-lo", str(args.prompt_lo),
           "--prompt-hi", str(args.prompt_hi),
           "--new-hi", str(args.new_hi),
           "--slots", str(args.slots),
           "--page-size", str(args.page_size),
           "--publish-interval", str(args.publish_interval),
           "--spawned-at", f"{time.time():.6f}"]
    if getattr(args, "fault_delay", 0.0):
        cmd += ["--fault-delay", str(args.fault_delay)]
    if getattr(args, "audit_interval", None) is not None:
        cmd += ["--audit-interval", str(args.audit_interval)]
    if getattr(args, "corrupt_after", 0):
        cmd += ["--corrupt-after", str(args.corrupt_after)]
    if getattr(args, "warm_dir", None):
        # ship the warm store: the child loads serialized executables
        # instead of compiling, so restarts/scale-ups reach ready fast
        cmd += ["--warm-dir", str(args.warm_dir)]
    proc = subprocess.Popen(cmd, cwd=root, env=env,
                            stdout=subprocess.PIPE, stderr=sys.stderr,
                            text=True)
    ready_box = {}
    ready_evt = threading.Event()

    def _read():
        for line in proc.stdout:
            line = line.strip()
            if not ready_evt.is_set() and line.startswith("{"):
                try:
                    obj = json.loads(line)
                except ValueError:
                    obj = None
                if isinstance(obj, dict) \
                        and obj.get("event") == "ready":
                    ready_box.update(obj)
                    ready_evt.set()
                    continue
            if line:
                print(f"[{name}] {line}", file=sys.stderr)
        proc.stdout.close()

    t = threading.Thread(target=_read, name=f"singa-route-io-{name}",
                         daemon=True)
    t.start()
    deadline = time.monotonic() + ready_timeout_s
    while not ready_evt.wait(0.2):
        if proc.poll() is not None:
            raise RuntimeError(
                f"replica {name} exited rc={proc.returncode} before "
                "ready")
        if time.monotonic() > deadline:
            proc.kill()
            raise RuntimeError(f"replica {name} not ready after "
                               f"{ready_timeout_s}s")
    return proc, dict(ready_box)


# ---- the kill-and-replace A/B harness ---------------------------------------

def _ab_arm(args, workdir: str, *, kill: bool,
            fault_delay: float = 0.0) -> dict:
    """One harness arm: N replicas under the seeded Poisson workload.
    With `kill`, SIGKILL one replica mid-traffic and join a (pre-warmed)
    standby in its place; with `fault_delay`, every replica stalls each
    engine step by that much (the tail-attribution probe). Returns
    per-request outcomes/tokens, the router's counters, the tail
    summary + per-request attribution sums, each replica's cold-start
    breakdown, and (kill arm) the merged-trace flow checks — the
    caller does the cross-arm asserts."""
    from types import SimpleNamespace

    from . import diag, fleet, serving, slo
    fleet_dir = os.path.join(workdir, "spool")
    os.makedirs(fleet_dir, exist_ok=True)
    agg = fleet.install_aggregator(fleet_dir, stale_after_s=60.0,
                                   poll_interval_s=0.05)
    diag.start_diag_server(port=0)
    spawn_args = SimpleNamespace(**vars(args))
    spawn_args.fault_delay = fault_delay
    r = Router(fleet_dir=fleet_dir,
               queue_limit=max(64, 4 * args.requests),
               max_attempts=8, retry_base_s=0.05, retry_max_s=1.0,
               retry_total_s=args.timeout, retry_seed=args.seed,
               health_interval_s=0.05, liveness_floor_s=1.0,
               liveness_ceiling_s=15.0).start()
    arm = {"kill": kill}
    try:
        names = [f"r{i}" for i in range(args.replicas)]
        spawn_names = names + ([f"r{args.replicas}"] if kill else [])
        spawned = {}
        threads = []
        errs = {}

        def _spawn_one(n):
            try:
                spawned[n] = spawn_replica(n, fleet_dir, spawn_args)
            except Exception as e:  # surfaced after the join below
                errs[n] = e

        for n in spawn_names:
            t = threading.Thread(target=_spawn_one, args=(n,),
                                 name=f"singa-route-spawn-{n}",
                                 daemon=True)
            threads.append(t)
            t.start()
        for t in threads:
            t.join()
        if errs:
            raise RuntimeError(f"replica spawn failed: {errs}")
        for n in names:
            proc, ready = spawned[n]
            r.add_replica(
                n, f"http://127.0.0.1:{ready['ctl_port']}", host=n,
                diag_url=f"http://127.0.0.1:{ready['diag_port']}",
                proc=proc)
        standby = spawned.get(f"r{args.replicas}")

        wl = serving.poisson_workload(
            args.seed, args.requests, args.rps, args.vocab,
            (args.prompt_lo, args.prompt_hi), (4, args.new_hi))
        kill_at = max(1, int(args.kill_frac * args.requests))
        victim = names[1 % len(names)]
        handles = []
        t0 = time.perf_counter()
        killed_ts = None
        for i in range(args.requests):
            dt = t0 + wl["arrivals"][i] - time.perf_counter()
            if dt > 0:
                time.sleep(dt)
            handles.append(r.submit(wl["prompts"][i],
                                    int(wl["new_lens"][i])))
            if kill and killed_ts is None and i >= kill_at:
                # SIGKILL, not terminate: the replica gets no chance to
                # drain — this is the crash the failover path exists
                # for. Prefer the moment the victim has a request IN
                # FLIGHT (spin briefly after the submit; at low rps the
                # request would otherwise finish between arrivals), so
                # the run provably exercises mid-request failover, and
                # force the kill within a few arrivals regardless.
                vrep = r.get_replica(victim)
                spin = time.perf_counter() + 0.25
                while time.perf_counter() < spin \
                        and not vrep.inflight:
                    time.sleep(0.001)
                # ...and hold the trigger until the victim's ACCEPTED
                # work has provably reached its shard file (the
                # handle_submit force-publish): the merged trace's
                # victim track only exists if the in-flight timeline
                # hit disk before the SIGKILL. Bounded — a request
                # that completes first just means a later arrival
                # re-arms the trigger.
                published = False
                spin = time.perf_counter() \
                    + 6.0 * args.publish_interval
                while time.perf_counter() < spin and vrep.inflight:
                    agg.poll()
                    if any(w.host == victim
                           and isinstance(w.serve, dict)
                           and w.serve.get("active")
                           for w in agg._workers.values()):
                        published = True
                        break
                    time.sleep(0.005)
                if not (vrep.inflight and published) \
                        and i < kill_at + 8 \
                        and i < args.requests - 1:
                    continue
                vrep.proc.kill()
                killed_ts = time.perf_counter() - t0
                sproc, sready = standby
                r.add_replica(
                    f"r{args.replicas}",
                    f"http://127.0.0.1:{sready['ctl_port']}",
                    host=f"r{args.replicas}",
                    diag_url=f"http://127.0.0.1:{sready['diag_port']}",
                    proc=sproc)
        stuck = [h.id for h in handles if not h.wait(args.timeout)]
        snap = r.snapshot()
        fleetz = fleet.fleet_report()
        arm["tail"] = slo.tail_summary()
        # the wall-sum property, per terminal request: the LATENCY_ATTR
        # buckets must reconstruct the request's total wall time
        arm["attr_checks"] = [
            {"id": h.id, "outcome": h.outcome,
             "total_s": round(h.finished_ts - h.submitted, 6),
             "attr_sum": round(sum((h.attr or {}).values()), 6)}
            for h in handles if h.outcome is not None
            and h.finished_ts is not None]
        arm["startup"] = {n: ready.get("startup")
                          for n, (_, ready) in spawned.items()}
        arm["spawn_to_first_token_s"] = {
            n: ready.get("spawn_to_first_token_s")
            for n, (_, ready) in spawned.items()}
        if kill:
            # merged-trace flow check on a request that provably
            # failed over FROM the victim and completed elsewhere:
            # its trace_ctx flow must step through the router track
            # AND both replica tracks (the victim's partial work
            # survives in its last published shard)
            time.sleep(3.0 * args.publish_interval)
            agg.poll()
            pick = None
            for h in handles:
                if h.outcome != OUTCOME_COMPLETED:
                    continue
                if victim in {i.get("replica")
                              for e, _, i in h.events
                              if e == "failover"}:
                    pick = h
                    break
            arm["trace_checks"] = (
                _check_merged_trace(agg.trace_events(), pick.trace,
                                    os.getpid())
                if pick is not None else None)
        arm.update({
            "stuck": stuck,
            "outcomes": {h.id: h.outcome for h in handles},
            "tokens": {h.id: list(h.tokens) for h in handles
                       if h.outcome == OUTCOME_COMPLETED},
            "served_by": sorted({h.replica for h in handles
                                 if h.replica is not None}),
            "ttfts": [h.ttft_s for h in handles
                      if h.ttft_s is not None],
            "attempts_max": max((h.attempts for h in handles),
                                default=0),
            "failovers": snap["failovers"]["replica_dead"]
            + snap["failovers"]["drain"],
            "retries": snap["retries"],
            "reasons": snap["reasons"],
            "replica_states": {rep["name"]: rep["state"]
                               for rep in snap["replicas"]},
            "killed_at_s": killed_ts,
            "victim": victim if kill else None,
            "fleetz_has_router": "== router ==" in fleetz,
        })
        if kill and standby is not None \
                and f"r{args.replicas}" not in {
                    rep["name"] for rep in snap["replicas"]}:
            # kill_at was never reached (tiny workloads): retire the
            # unused standby so nothing leaks
            standby[0].kill()
            standby[0].wait(timeout=10.0)
        return arm
    finally:
        r.stop()
        reset()
        fleet.uninstall()
        diag.stop_diag_server()
        slo.tail_reset()  # each arm's /tailz view stands alone


def _check_merged_trace(trace: dict, trace_id, router_pid) -> dict:
    """Schema + flow checks over a merged fleet trace for ONE routed
    request's trace-context id: exactly one process_name per pid,
    every per-replica req_flow id scoped to its own pid (no
    cross-linked requests), and the trace_ctx flow for `trace_id`
    stepping s (router) -> t (each replica that touched it) -> f
    (router) in timestamp order across at least two replica pids."""
    events = trace.get("traceEvents") or []
    pname: "dict[int, int]" = {}
    bad_scope = 0
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            pname[e["pid"]] = pname.get(e["pid"], 0) + 1
        if e.get("cat") == "req_flow" \
                and e.get("ph") in ("s", "t", "f") \
                and not str(e.get("id", "")).startswith(
                    f"{e.get('pid')}:"):
            bad_scope += 1
    from .slo import TRACE_CTX_CAT
    steps = [e for e in events
             if e.get("cat") == TRACE_CTX_CAT
             and str(e.get("id")) == str(trace_id)]
    s_ev = [e for e in steps if e.get("ph") == "s"]
    t_ev = [e for e in steps if e.get("ph") == "t"]
    f_ev = [e for e in steps if e.get("ph") == "f"]
    rep_pids = sorted({e["pid"] for e in t_ev
                       if e["pid"] != router_pid})
    ordered = bool(
        len(s_ev) == 1 and len(f_ev) == 1 and t_ev
        and all(s_ev[0]["ts"] < e["ts"] < f_ev[0]["ts"]
                for e in t_ev))
    out = {
        "one_name_per_pid": bool(pname) and all(
            v == 1 for v in pname.values()),
        "req_flow_ids_pid_scoped": bad_scope == 0,
        "router_anchors": len(s_ev) == 1 and len(f_ev) == 1
        and all(e["pid"] == router_pid for e in s_ev + f_ev),
        "replica_pids": rep_pids,
        "spans_two_replicas": len(rep_pids) >= 2,
        "flow_ordered": ordered,
    }
    out["ok"] = bool(
        out["one_name_per_pid"] and out["req_flow_ids_pid_scoped"]
        and out["router_anchors"] and out["spans_two_replicas"]
        and out["flow_ordered"])
    return out


def _ab_main(args) -> int:
    from types import SimpleNamespace

    from . import engine
    base = tempfile.mkdtemp(prefix="singa_router_ab_")
    rec = {"replicas": args.replicas, "requests": args.requests,
           "rps": args.rps, "seed": args.seed, "ok": False}
    # the fault arm is a small third run: every replica stalls each
    # engine step by --fault-delay, so /tailz must rank decode as the
    # top p99 contributor — the attribution pipeline proven end to end
    fault_args = SimpleNamespace(**vars(args))
    fault_args.replicas = min(2, args.replicas)
    fault_args.requests = min(8, args.requests)
    try:
        clean = _ab_arm(args, os.path.join(base, "clean"), kill=False)
        kill = _ab_arm(args, os.path.join(base, "kill"), kill=True)
        fault = _ab_arm(fault_args, os.path.join(base, "fault"),
                        kill=False,
                        fault_delay=args.fault_delay or 0.05)
    finally:
        import shutil
        shutil.rmtree(base, ignore_errors=True)
    n = args.requests
    clean_done = sum(1 for o in clean["outcomes"].values()
                     if o == OUTCOME_COMPLETED)
    kill_done = sum(1 for o in kill["outcomes"].values()
                    if o == OUTCOME_COMPLETED)
    # zero loss: every submit terminal, and through the kill every one
    # COMPLETED (the retry budget is sized so nothing exhausts)
    lost = len(kill["stuck"]) + sum(
        1 for o in kill["outcomes"].values() if o is None)
    matched = all(kill["tokens"].get(rid) == toks
                  for rid, toks in clean["tokens"].items())
    victim_dead = kill["replica_states"].get(kill["victim"]) \
        == STATE_DEAD
    standby_served = f"r{args.replicas}" in kill["served_by"]
    p99_clean = engine.pctile(clean["ttfts"], 0.99)
    p99_kill = engine.pctile(kill["ttfts"], 0.99)
    # per-request attribution must reconstruct each wall time within
    # 10% (plus a small absolute floor for sub-ms rejects)
    attr_ok = all(
        abs(c["attr_sum"] - c["total_s"])
        <= max(0.10 * c["total_s"], 0.005)
        for arm in (clean, kill, fault)
        for c in arm["attr_checks"])
    attr_n = sum(len(arm["attr_checks"])
                 for arm in (clean, kill, fault))
    trace_checks = kill.get("trace_checks")
    fault_top = (fault.get("tail") or {}).get("top")
    decode_p99 = (((fault.get("tail") or {}).get("buckets") or {})
                  .get("decode") or {}).get("p99_s")
    cold_vals = [v for v in
                 clean["spawn_to_first_token_s"].values()
                 if v is not None]
    cold_p50 = engine.pctile(cold_vals, 0.5)
    warm_p50 = engine.pctile(clean["ttfts"], 0.5)
    startup0 = clean["startup"].get("r0") or {}
    rec.update({
        "clean_completed": clean_done, "kill_completed": kill_done,
        "lost_requests": lost,
        "kill_outcomes": {o: sum(1 for v in kill["outcomes"].values()
                                 if v == o) for o in ROUTE_OUTCOMES},
        "failovers": kill["failovers"], "retries": kill["retries"],
        "tokens_match_clean_arm": matched,
        "victim_marked_dead": victim_dead,
        "standby_served": standby_served,
        "killed_at_s": kill["killed_at_s"],
        "fleetz_has_router_rows": bool(clean["fleetz_has_router"]
                                       and kill["fleetz_has_router"]),
        "ttft_p99_clean_s": p99_clean, "ttft_p99_kill_s": p99_kill,
        "ttft_p99_delta_s": (round(p99_kill - p99_clean, 6)
                             if p99_clean is not None
                             and p99_kill is not None else None),
        "attr_sum_ok": attr_ok, "attr_checked_requests": attr_n,
        "trace": trace_checks,
        "fault_top_bucket": fault_top,
        "fault_completed": sum(
            1 for o in fault["outcomes"].values()
            if o == OUTCOME_COMPLETED),
        "startup_phases": startup0,
        "cold_spawn_first_token_s": cold_p50,
        "cold_warm_first_token_delta_s": (
            round(cold_p50 - warm_p50, 6)
            if cold_p50 is not None and warm_p50 is not None
            else None),
    })
    rec["ok"] = bool(
        clean_done == n and kill_done == n and lost == 0 and matched
        and victim_dead and standby_served
        and kill["failovers"] >= 1
        and rec["fleetz_has_router_rows"]
        and p99_clean is not None and p99_kill is not None
        and attr_ok and attr_n >= 2 * n
        and trace_checks is not None and trace_checks["ok"]
        and fault_top == "decode"
        and set(startup0) == set(STARTUP_PHASES)
        and cold_p50 is not None and warm_p50 is not None
        and cold_p50 > warm_p50)
    lines = [
        {"metric": "router_lost_requests", "value": float(lost),
         "unit": "count"},
        {"metric": "router_failover_requests",
         "value": float(kill["failovers"]), "unit": "count"},
        {"metric": "router_ttft_p99_clean_s",
         "value": float(p99_clean or 0.0), "unit": "s"},
        {"metric": "router_ttft_p99_kill_s",
         "value": float(p99_kill or 0.0), "unit": "s"},
        {"metric": "router_cold_spawn_first_token_s",
         "value": float(cold_p50 or 0.0), "unit": "s"},
        {"metric": "router_cold_warm_first_token_delta_s",
         "value": float(rec["cold_warm_first_token_delta_s"] or 0.0),
         "unit": "s"},
        {"metric": "replica_startup_total_s",
         "value": float(round(sum(startup0.values()), 6)
                        if startup0 else 0.0), "unit": "s"},
        {"metric": "router_tailz_decode_p99_contrib_s",
         "value": float(decode_p99 or 0.0), "unit": "s"},
        rec,
    ]
    with open(args.out, "w", encoding="utf-8") as f:
        for obj in lines:
            f.write(json.dumps(obj, sort_keys=True) + "\n")
    print(json.dumps(rec, indent=2, sort_keys=True))
    return 0 if rec["ok"] else 1


# ---- the cold-vs-warm spawn A/B ---------------------------------------------

def _warm_probe(ctl_port: int, args, rid: int = 1) -> "list[int]":
    """One seeded deterministic probe against a replica's control
    surface; returns its greedy tokens. Run against both A/B arms, the
    token lists must be identical — executables loaded from the warm
    store must compute exactly what fresh compiles compute."""
    rng = np.random.RandomState(int(args.seed))
    prompt = rng.randint(1, int(args.vocab),
                         size=max(1, int(args.prompt_lo))).tolist()
    deadline = time.monotonic() + float(args.timeout)
    while True:
        out = _http_json(f"http://127.0.0.1:{ctl_port}/submit",
                         {"rid": int(rid), "prompt": prompt,
                          "max_new": max(1, min(8, int(args.new_hi))),
                          "wait_s": 10.0}, timeout=30.0)
        if out.get("outcome") == "completed":
            return [int(t) for t in out["tokens"]]
        if out.get("outcome") != "pending":
            raise RuntimeError(f"warm A/B probe failed: {out}")
        if time.monotonic() > deadline:
            raise RuntimeError("warm A/B probe timed out")


def _warm_ab_main(args) -> int:
    """The zero-compile-restart A/B: spawn a COLD replica against an
    empty warm store (every staged executable compiles fresh and is
    exported), shut it down, then spawn a WARM replica — a genuinely
    fresh Python process — against the SAME store. The warm arm must
    prove, from the outside:

      * its staged builds were store HITS across the process boundary
        (and the cold arm's were misses that exported),
      * its XLA compile seconds collapsed to <= --warm-compile-frac of
        the cold arm's,
      * its spawn-to-first-token beat cold by >= --warm-speedup, and
      * a fixed seeded probe decodes token-identical tokens on both
        arms — loading serialized executables must not change what the
        model computes.

    Writes the JSONL artifact (metric rows + a final rec with "ok") to
    args.out. The `spawn_to_first_token_s` / `compile_cache_hit_rate`
    rows feed tools/bench_trend.py's regression tracking."""
    import shutil
    import tempfile
    from types import SimpleNamespace

    workdir = tempfile.mkdtemp(prefix="singa-warmab-")
    fleet_dir = os.path.join(workdir, "spool")
    os.makedirs(fleet_dir, exist_ok=True)
    cargs = SimpleNamespace(**vars(args))
    cargs.warm_dir = os.path.join(workdir, "warmstore")
    cargs.fault_delay = 0.0
    cargs.corrupt_after = 0
    arms = {}
    try:
        for arm, name in (("cold", "w0"), ("warm", "w1")):
            print(f"[warm-ab] spawning {arm} replica {name} "
                  f"(store: {cargs.warm_dir})", file=sys.stderr)
            proc, ready = spawn_replica(
                name, fleet_dir, cargs, ready_timeout_s=args.timeout)
            try:
                toks = _warm_probe(ready["ctl_port"], args)
            finally:
                try:
                    _http_json(
                        f"http://127.0.0.1:{ready['ctl_port']}"
                        "/shutdown", {}, timeout=10.0)
                    proc.wait(timeout=30.0)
                except Exception:
                    proc.kill()
                    proc.wait(timeout=10.0)
            arms[arm] = {"ready": ready, "tokens": toks}
    finally:
        shutil.rmtree(workdir, ignore_errors=True)

    cold, warm = arms["cold"]["ready"], arms["warm"]["ready"]
    c_compile = float(cold.get("startup", {}).get("compile", 0.0))
    w_compile = float(warm.get("startup", {}).get("compile", 0.0))
    c_sft = cold.get("spawn_to_first_token_s")
    w_sft = warm.get("spawn_to_first_token_s")
    c_look = (cold.get("warm") or {}).get("lookups") or {}
    w_look = (warm.get("warm") or {}).get("lookups") or {}
    hit_rate = (warm.get("warm") or {}).get("hit_rate")
    # the floor keeps the frac check meaningful when the model is so
    # small that cold compile itself is noise-level
    frac = w_compile / max(c_compile, 1e-9)
    speedup = (float(c_sft) / float(w_sft)
               if c_sft and w_sft and float(w_sft) > 0 else 0.0)
    checks = {
        "cold_exported": (cold.get("warm") or {}).get("exports", 0) > 0,
        "cold_no_hits": int(c_look.get("hit", 0)) == 0,
        "warm_hits_across_process": int(w_look.get("hit", 0)) > 0,
        "warm_no_fallbacks": sum(
            int(w_look.get(k, 0))
            for k in ("miss", "stale", "corrupt")) == 0,
        "warm_compile_frac_ok":
            frac <= float(args.warm_compile_frac),
        "warm_spawn_speedup_ok":
            speedup >= float(args.warm_speedup),
        "tokens_match":
            arms["cold"]["tokens"] == arms["warm"]["tokens"],
    }
    rec = {
        "bench": "router_warm_ab", "schema": 1,
        "seed": int(args.seed),
        "model": {"vocab": int(args.vocab), "dim": int(args.dim),
                  "layers": int(args.layers)},
        "thresholds": {
            "warm_compile_frac": float(args.warm_compile_frac),
            "warm_speedup": float(args.warm_speedup)},
        "cold": {"startup": cold.get("startup"),
                 "spawn_to_first_token_s": c_sft,
                 "warm": cold.get("warm")},
        "warm": {"startup": warm.get("startup"),
                 "spawn_to_first_token_s": w_sft,
                 "warm": warm.get("warm")},
        "compile_frac": round(frac, 6),
        "spawn_speedup": round(speedup, 6),
        "checks": checks,
        "ok": all(checks.values()),
    }
    lines = [
        {"metric": "warmab_cold_compile_s", "value": c_compile,
         "unit": "s"},
        {"metric": "warmab_warm_compile_s", "value": w_compile,
         "unit": "s"},
        {"metric": "spawn_to_first_token_cold_s",
         "value": float(c_sft or 0.0), "unit": "s"},
        {"metric": "spawn_to_first_token_s",
         "value": float(w_sft or 0.0), "unit": "s"},
        {"metric": "compile_cache_hit_rate",
         "value": float(hit_rate or 0.0), "unit": "ratio"},
        {"metric": "warmab_spawn_speedup", "value": float(speedup),
         "unit": "ratio"},
        rec,
    ]
    with open(args.out, "w", encoding="utf-8") as f:
        for obj in lines:
            f.write(json.dumps(obj, sort_keys=True) + "\n")
    print(json.dumps(rec, indent=2, sort_keys=True))
    return 0 if rec["ok"] else 1


# ---- CLI --------------------------------------------------------------------

def main(argv=None) -> int:
    import argparse
    p = argparse.ArgumentParser(
        prog="python -m singa_tpu.router",
        description="serving control plane: --replica runs one serving "
                    "replica; --ab runs the kill-and-replace harness; "
                    "--warm-ab runs the cold-vs-warm spawn A/B")
    p.add_argument("--replica", action="store_true")
    p.add_argument("--ab", action="store_true")
    p.add_argument("--warm-ab", action="store_true",
                   help="spawn a cold replica against an empty warm "
                        "store, then a warm one against the same store; "
                        "prove zero-compile restart (see _warm_ab_main)")
    p.add_argument("--name", default="r0")
    p.add_argument("--fleet-dir", default=None)
    p.add_argument("--replicas", type=int, default=3)
    p.add_argument("--requests", type=int, default=32)
    p.add_argument("--rps", type=float, default=4.0)
    p.add_argument("--seed", type=int, default=1234)
    p.add_argument("--kill-frac", type=float, default=0.35,
                   help="kill the victim after this fraction of "
                        "submits (kill arm)")
    p.add_argument("--vocab", type=int, default=211)
    p.add_argument("--dim", type=int, default=64)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--prompt-lo", type=int, default=4)
    p.add_argument("--prompt-hi", type=int, default=12)
    p.add_argument("--new-hi", type=int, default=24)
    p.add_argument("--slots", type=int, default=4)
    p.add_argument("--page-size", type=int, default=8)
    p.add_argument("--publish-interval", type=float, default=0.1)
    p.add_argument("--spawned-at", type=float, default=None,
                   help="replica mode: the parent's time.time() at "
                        "spawn — anchors the cold-start observatory's "
                        "spawn phase and spawn-to-first-token")
    p.add_argument("--fault-delay", type=float, default=0.0,
                   help="replica mode: install a FaultPlan delay of "
                        "this many seconds on every serving.engine_step "
                        "(the --ab fault arm's tail-attribution probe)")
    p.add_argument("--audit-interval", type=float, default=0.25,
                   help="replica mode: param-fingerprint recompute "
                        "period in seconds (0 disables the timer; the "
                        "startup fingerprint is always computed)")
    p.add_argument("--corrupt-after", type=int, default=0,
                   help="replica mode: bit-flip one param layer at the "
                        "Nth fingerprint tick via fault_point("
                        "'audit.corrupt_params') — the audit --ab "
                        "corrupt arm's SDC injection")
    p.add_argument("--warm-dir", default=None,
                   help="warm-store root: replicas persist serialized "
                        "executables + the XLA compile cache here and "
                        "load them on restart (replica/--ab modes; "
                        "--warm-ab manages its own store)")
    p.add_argument("--warm-compile-frac", type=float, default=0.10,
                   help="--warm-ab: warm arm's XLA compile seconds "
                        "must be <= this fraction of the cold arm's")
    p.add_argument("--warm-speedup", type=float, default=3.0,
                   help="--warm-ab: warm spawn-to-first-token must "
                        "beat cold by at least this factor")
    p.add_argument("--timeout", type=float, default=600.0)
    p.add_argument("--out", default=None)
    args = p.parse_args(argv)
    if args.out is None:
        args.out = "WARM_r01.json" if args.warm_ab else "SERVE_r01.json"
    if args.replica:
        if not args.fleet_dir:
            p.error("--replica needs --fleet-dir")
        return _replica_main(args)
    if args.warm_ab:
        return _warm_ab_main(args)
    if args.ab:
        return _ab_main(args)
    p.error("pick a mode: --replica, --ab, or --warm-ab")
    return 2


__all__ = [
    "ROUTE_OUTCOMES", "ROUTE_REASONS", "REPLICA_STATES",
    "STARTUP_PHASES",
    "Router", "RouterRequest", "Replica", "ReplicaControl",
    "install_router", "get_router", "reset",
    "serving_lines", "fleetz_lines", "router_report",
    "router_json", "router_trace_events",
    "spawn_replica",
]

if __name__ == "__main__":
    # run under the CANONICAL module (not the runpy __main__ alias): the
    # CLI installs module singletons the diag/fleet layers reach via
    # `import singa_tpu.router`
    from singa_tpu.router import main as _main
    sys.exit(_main())
