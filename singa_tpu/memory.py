"""HBM memory observatory: the live device-memory ledger.

The reference's signature feature is a buffered graph ANALYZED for memory
reuse (scheduler.cc per SURVEY §0) — but neither it nor our introspect
layer can answer "what is on the device right now, and who put it
there": `observe.record_hbm` mirrors `jax.Device.memory_stats()` (None
on backends without allocator stats, e.g. the tier-1 CPU suite) and
introspect's `memory_analysis` is a static per-executable ESTIMATE.
This module is the dynamic half of the memory model:

  - **MemoryLedger**: enumerates `jax.live_arrays()` (backend-agnostic,
    so it works — and is testable — on CPU) and attributes every live
    buffer to a declared region from `MEM_REGIONS` via lightweight
    registration hooks at the sites where arrays are born: model params
    (`model.py`), optimizer slots (`opt.py`), the device prefetch ring
    (`overlap.py`), serving KV caches (`serving.py`), and
    flight-recorder batch snapshots (`health.py`). Anything unclaimed
    lands in `unattributed` — so the regions always RECONCILE: the sum
    of `singa_mem_region_bytes{region=...}` equals the live-array byte
    total at every snapshot, by construction (test-enforced).

  - **Timeline ring**: one bounded deque of per-step snapshots (the
    ledger snapshots on every `model.step` span exit, and on
    `serving.decode` so KV caches are visible mid-call), exported as
    `singa_mem_region_bytes` / `singa_mem_live_arrays` gauges and the
    `/memz` diag endpoint (breakdown + timeline + the static introspect
    HBM view side-by-side, for estimate-vs-actual drift).

  - **Leak detector**: a sustained positive slope of total live bytes
    after warmup feeds `HealthMonitor.note_external(KIND_MEM_LEAK)`
    under the monitor's (or an explicit) warn/halt policy; the region
    with the largest positive delta over the window names the suspect.

  - **OOM forensics**: step dispatch (`model._invoke_step`) and the
    serving AOT executors (`introspect.AotExecutor`) call
    `handle_oom()` on a resource-exhausted `XlaRuntimeError` before
    re-raising — a FlightRecorder-style JSONL bundle (timeline, region
    breakdown, top-K largest live arrays with shapes/dtypes, the
    executable manifest) lands on disk, round-tripped by
    `health.load_flight_bundle`, so a production OOM dies with a
    post-mortem instead of a bare RESOURCE_EXHAUSTED.

  - **Pre-flight fit**: `estimate_fit(model, batch)` combines
    introspect's arguments/temps/outputs analysis with the ledger's
    param+opt bytes against the device limit (memory_stats
    `bytes_limit`, or `SINGA_TPU_HBM_LIMIT_BYTES`), surfaced in the
    explain report and the `bench.py --mem` arm.

Overhead contract: every snapshot is host-side bookkeeping over object
identities — nothing traces, so `compile_count` stays 1 with the ledger
installed (test-enforced; the bound is measured by `bench.py --mem`).
"""

from __future__ import annotations

import json
import os
import threading
import time
import weakref
from collections import deque

import jax

from . import observe

# ---- regions (the lint in tools/check_metrics_names.py greps this) --------

#: Every region a live device buffer can be attributed to. Attribution
#: is first-match in THIS order (params before opt_state before caches),
#: with `unattributed` the catch-all — so each array lands in exactly
#: one region and the per-region bytes always sum to the live total.
MEM_REGIONS = ("params", "opt_state", "prefetch_ring", "kv_cache",
               "flight_snapshot", "unattributed")
REGION_PARAMS = "params"
REGION_OPT_STATE = "opt_state"
REGION_PREFETCH_RING = "prefetch_ring"
REGION_KV_CACHE = "kv_cache"
REGION_FLIGHT_SNAPSHOT = "flight_snapshot"
REGION_UNATTRIBUTED = "unattributed"

#: span leaves whose exit triggers a ledger snapshot. Train steps are
#: NOT snapshotted at span exit — the model.step span closes after the
#: donated pre-step buffers died but before the new state is assigned
#: back, so params would misattribute; steps ride the post-commit
#: `observe.add_step_listener` hook instead. The serving decode span
#: exit is the only moment the KV caches are live host-visible buffers;
#: the engine's per-sync step span keeps the page-pool occupancy on the
#: /memz timeline for processes that only serve (no train steps), and
#: the engine-prefill span catches the admission seam, where a new
#: request's pages were just written into the pool.
SNAPSHOT_SPAN_LEAVES = ("serving.decode", "serving.engine_step",
                        "serving.engine_prefill")

#: top-K largest live arrays embedded in an OOM bundle
OOM_TOP_K = 16


# ---- birth-site registry ---------------------------------------------------
# Providers persist independently of any installed ledger: the hooks in
# model/opt/overlap fire at object-construction time, which may predate
# install_ledger(). Each provider is a zero-arg callable returning the
# CURRENT arrays of its region (params change identity every donated
# step, so a snapshot must re-ask, not cache ids).

_lock = threading.RLock()
_providers: "dict[tuple[str, int], callable]" = {}
_transients: "dict[int, tuple[weakref.ref, str]]" = {}


def _check_region(region: str):
    if region not in MEM_REGIONS:
        raise ValueError(f"region {region!r} not in {MEM_REGIONS}")


def _cleanup_providers(key_id: int, regions):
    """Weakref callback factory: when a tracked object dies, its
    provider entries are dropped — without this, a long-lived process
    that rebuilds models/optimizers would accumulate dead closures in
    _providers and every snapshot would keep calling them."""

    def _cb(_ref):
        with _lock:
            for rg in regions:
                _providers.pop((rg, key_id), None)

    return _cb


def register_provider(region: str, key, fn):
    """Register `fn() -> arrays` as the current contents of `region`
    (keyed, so re-registration for the same object replaces). The hook
    is a dict write — cheap enough for construction paths."""
    _check_region(region)
    with _lock:
        _providers[(region, id(key) if not isinstance(key, int) else key)] \
            = fn
    return fn


def unregister_provider(region: str, key):
    with _lock:
        _providers.pop(
            (region, id(key) if not isinstance(key, int) else key), None)


def region_has_provider(region: str) -> bool:
    """True when a persistent birth-site provider owns `region` — the
    serving decode path consults this to skip its transient
    note_arrays(kv_cache) once an engine's page pool is registered
    (the provider is authoritative; a second transient claim would be
    redundant weakref churn on every call)."""
    _check_region(region)
    with _lock:
        return any(rg == region for (rg, _k) in _providers)


def _iter_arrays(obj):
    """Yield every jax.Array reachable from `obj` (tuples/lists/dicts,
    Tensor-likes via `.data`); non-array leaves are skipped."""
    if obj is None:
        return
    if isinstance(obj, jax.Array):
        yield obj
        return
    data = getattr(obj, "data", None)
    if isinstance(data, jax.Array):
        yield data
        return
    if isinstance(obj, dict):
        for v in obj.values():
            yield from _iter_arrays(v)
    elif isinstance(obj, (tuple, list)):
        for v in obj:
            yield from _iter_arrays(v)


def note_arrays(region: str, tree):
    """Transiently attribute every array in `tree` to `region` for as
    long as the buffers stay alive (weakref-keyed, so a freed buffer —
    or an id reused after GC — can never be misattributed). The
    serving decode uses this for KV caches, health for flight-recorder
    batch snapshots."""
    _check_region(region)
    n = 0
    with _lock:
        for a in _iter_arrays(tree):
            aid = id(a)

            def _drop(_ref, _aid=aid):
                with _lock:
                    _transients.pop(_aid, None)

            try:
                _transients[aid] = (weakref.ref(a, _drop), region)
                n += 1
            except TypeError:
                continue  # unexpected non-weakrefable leaf: skip
    return n


def track_model(model):
    """model.py's birth-site hook (called from `_build_step_impl`):
    params follow the model's CURRENT param buffers (donation replaces
    them every step), and the retained step inputs — kept for the
    flight recorder's batch provider — attribute to `flight_snapshot`
    while a health monitor is attached."""
    key_id = id(model)
    ref = weakref.ref(model, _cleanup_providers(
        key_id, (REGION_PARAMS, REGION_FLIGHT_SNAPSHOT)))

    def params():
        m = ref()
        if m is None:
            return ()
        try:
            return [t.data for t in m.get_params().values()]
        except Exception:
            return ()

    def flight():
        m = ref()
        if m is None or getattr(m, "_health_monitor", None) is None:
            return ()
        return getattr(m, "_last_input_arrs", None) or ()

    register_provider(REGION_PARAMS, key_id, params)
    register_provider(REGION_FLIGHT_SNAPSHOT, key_id, flight)


def track_optimizer(opt):
    """opt.py's birth-site hook (called from `Optimizer.setup`): slot
    buffers + the step counter, re-read per snapshot (strategies with
    lazily growing state — sparse residuals — stay covered)."""
    key_id = id(opt)
    ref = weakref.ref(opt, _cleanup_providers(key_id,
                                              (REGION_OPT_STATE,)))

    def slots():
        o = ref()
        if o is None:
            return ()
        try:
            return list(o.state_arrays())
        except Exception:
            return ()

    register_provider(REGION_OPT_STATE, key_id, slots)


def track_prefetcher(prefetcher):
    """overlap.py's birth-site hook (DevicePrefetcher.__init__): the
    on-device batches currently parked in the ring."""
    key_id = id(prefetcher)
    ref = weakref.ref(prefetcher, _cleanup_providers(
        key_id, (REGION_PREFETCH_RING,)))

    def ring():
        p = ref()
        if p is None:
            return ()
        try:
            items = list(p._ring)  # may include the _END sentinel:
        except Exception:          # _iter_arrays yields nothing for it
            return ()
        out = []
        for it in items:
            out.extend(_iter_arrays(it))
        return out

    register_provider(REGION_PREFETCH_RING, key_id, ring)


def untrack(region: str, obj):
    """Drop a birth-site registration (DevicePrefetcher.close)."""
    unregister_provider(region, obj)


def total_live_bytes() -> int:
    """Byte total over `jax.live_arrays()` — the backend-agnostic
    answer `observe.record_hbm` falls back to when the device exposes
    no allocator stats (the tier-1 CPU path)."""
    return sum(int(getattr(a, "nbytes", 0) or 0)
               for a in jax.live_arrays())


_fallback_cache = [float("-inf"), 0]  # [monotonic ts, bytes]


def hbm_fallback_bytes(max_age_s: float = 0.5) -> int:
    """The per-step-rate-safe spelling of `total_live_bytes` for
    `observe.record_hbm`: the installed ledger's latest snapshot total
    when one exists (O(1)), else a direct enumeration throttled to one
    per `max_age_s` — record_hbm runs on EVERY step, and a long-lived
    process can hold thousands of live arrays."""
    led = _ledger
    if led is not None and led.timeline:
        return int(led.timeline[-1]["total_bytes"])
    now = time.monotonic()
    if now - _fallback_cache[0] < max_age_s:
        return _fallback_cache[1]
    v = total_live_bytes()
    _fallback_cache[0] = now
    _fallback_cache[1] = v
    return v


# ---- leak detection --------------------------------------------------------

class LeakDetector:
    """Sustained-growth watchdog over the ledger's total-bytes series.

    After `warmup` snapshots, a least-squares slope over the last
    `window` snapshots above `min_slope_bytes` (per step) for `sustain`
    consecutive checks is a leak verdict: counted per suspect region
    (`singa_mem_leak_verdicts_total{region=...}`), fed to the active
    `HealthMonitor.note_external(KIND_MEM_LEAK)` under `policy` (None =
    the monitor's own warn/halt), and held until the slope drops back
    under the threshold (one verdict per episode, not one per step).
    """

    def __init__(self, warmup: int = 5, window: int = 8,
                 min_slope_bytes: float = 4096.0, sustain: int = 3,
                 policy: "str | None" = None):
        if policy is not None and policy not in ("warn", "halt"):
            raise ValueError(f"policy {policy!r} not in ('warn','halt')")
        self.warmup = int(warmup)
        self.window = max(2, int(window))
        self.min_slope_bytes = float(min_slope_bytes)
        self.sustain = int(sustain)
        self.policy = policy
        self.slope = 0.0
        self.verdicts: list = []
        self._seen = 0
        self._over = 0
        self._flagged = False

    @staticmethod
    def _fit_slope(ys):
        n = len(ys)
        xm = (n - 1) / 2.0
        ym = sum(ys) / n
        num = sum((i - xm) * (y - ym) for i, y in enumerate(ys))
        den = sum((i - xm) ** 2 for i in range(n))
        return num / den if den else 0.0

    def check(self, timeline, step=None) -> "dict | None":
        """Feed one snapshot tick; returns the verdict dict when a new
        leak episode is flagged, else None."""
        self._seen += 1
        if self._seen <= self.warmup or len(timeline) < self.window:
            return None
        tail = list(timeline)[-self.window:]
        self.slope = self._fit_slope([s["total_bytes"] for s in tail])
        if observe.is_enabled():
            observe.gauge(
                "singa_mem_leak_slope_bytes",
                "live-bytes growth per step over the leak-detector "
                "window").set(self.slope)
        if self.slope <= self.min_slope_bytes:
            self._over = 0
            self._flagged = False
            return None
        self._over += 1
        if self._over < self.sustain or self._flagged:
            return None
        self._flagged = True
        deltas = {r: tail[-1]["regions"].get(r, 0)
                  - tail[0]["regions"].get(r, 0) for r in MEM_REGIONS}
        suspect = max(deltas, key=lambda r: deltas[r])
        verdict = {
            "step": int(step) if step is not None else None,
            "slope_bytes_per_step": round(self.slope, 1),
            "suspect_region": suspect,
            "suspect_delta_bytes": int(deltas[suspect]),
            "window": self.window,
            "ts": round(time.time(), 6),
        }
        self.verdicts.append(verdict)
        assert suspect in MEM_REGIONS
        if observe.is_enabled():
            observe.counter(
                "singa_mem_leak_verdicts_total",
                "sustained live-bytes growth verdicts, by suspect region"
            ).inc(region=suspect)
            observe.get_registry().emit(
                {"kind": "mem", "event": "leak", **verdict})
        from . import health
        mon = health.active_monitor()
        if mon is not None:
            action = self.policy
            if action is None:
                action = "halt" if mon.policy == "halt" else "warn"
            try:
                verdict["action"] = mon.note_external(
                    health.KIND_MEM_LEAK, detail=dict(verdict),
                    step=step, action=action)
            except Exception:
                pass  # the monitor must never break the step path
        return verdict


# ---- the ledger ------------------------------------------------------------

class MemoryLedger:
    """Live device-memory ledger: snapshot on demand (or per step via
    the span listener `install_ledger` wires), keep a bounded timeline,
    export gauges, and run the leak detector.

    `interval_steps`: snapshot every Nth `model.step` exit (1 = every
    step). `sample_interval_s > 0` additionally starts a daemon sampler
    thread (``singa-mem-sampler``) for processes that never step (pure
    serving); `close()`/`uninstall_ledger`/`reset()` joins it (sampling
    ledgers register module-wide so the conftest teardown can reap one
    a test leaked even without install_ledger).

    `out_dir=None` (the default) means OOM bundles follow the active
    HealthMonitor's recorder directory — the one `/flightz` indexes —
    falling back to the CWD; pass an explicit path to pin it.
    """

    def __init__(self, timeline: int = 512, interval_steps: int = 1,
                 sample_interval_s: float = 0.0, leak: "LeakDetector | "
                 "bool | None" = True, out_dir: "str | None" = None,
                 top_k: int = OOM_TOP_K):
        self.timeline: "deque[dict]" = deque(maxlen=int(timeline))
        self.interval_steps = max(1, int(interval_steps))
        self.out_dir = str(out_dir) if out_dir is not None else None
        self.top_k = int(top_k)
        self.enabled = True
        self.leak = (LeakDetector() if leak is True
                     else (leak or None))
        self.steps_seen = 0
        self._snap_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = None
        if sample_interval_s > 0:
            self._thread = threading.Thread(
                target=self._sample_loop, args=(float(sample_interval_s),),
                name="singa-mem-sampler", daemon=True)
            with _lock:
                _samplers.append(self)
            self._thread.start()

    # -- attribution -------------------------------------------------------
    @staticmethod
    def _region_ids() -> "dict[int, str]":
        """id(array) -> region, built fresh from the providers and the
        transient notes; first region in MEM_REGIONS order wins."""
        with _lock:
            providers = list(_providers.items())
            transients = list(_transients.items())
        by_region: "dict[str, set[int]]" = {r: set() for r in MEM_REGIONS}
        for (region, _key), fn in providers:
            try:
                for a in _iter_arrays(fn()):
                    by_region[region].add(id(a))
            except Exception:
                continue  # a broken provider must not break the step
        for aid, (ref, region) in transients:
            if ref() is not None:
                by_region[region].add(aid)
        ids: "dict[int, str]" = {}
        for region in MEM_REGIONS:
            for aid in by_region[region]:
                ids.setdefault(aid, region)
        return ids

    def snapshot(self, step: "int | None" = None) -> dict:
        """One reconciled breakdown of everything live right now. The
        region sums equal the `jax.live_arrays()` byte total by
        construction — every live array is counted exactly once."""
        with self._snap_lock:
            ids = self._region_ids()
            regions = {r: 0 for r in MEM_REGIONS}
            counts = {r: 0 for r in MEM_REGIONS}
            total = 0
            n = 0
            for a in jax.live_arrays():
                r = ids.get(id(a), REGION_UNATTRIBUTED)
                nb = int(getattr(a, "nbytes", 0) or 0)
                regions[r] += nb
                counts[r] += 1
                total += nb
                n += 1
            snap = {
                "ts": round(time.time(), 6),
                "step": int(step) if step is not None
                else self.steps_seen,
                "regions": regions,
                "counts": counts,
                "total_bytes": total,
                "n_arrays": n,
            }
            self.timeline.append(snap)
            self._export(snap)
            return snap

    @staticmethod
    def _export(snap: dict):
        if not observe.is_enabled():
            return
        g = observe.gauge(
            "singa_mem_region_bytes",
            "live device bytes attributed to each ledger region")
        for region in MEM_REGIONS:
            g.set(float(snap["regions"][region]), region=region)
        observe.gauge("singa_mem_total_bytes",
                      "total live device bytes (jax.live_arrays)"
                      ).set(float(snap["total_bytes"]))
        observe.gauge("singa_mem_live_arrays",
                      "live device arrays (jax.live_arrays)"
                      ).set(float(snap["n_arrays"]))
        observe.counter("singa_mem_snapshots_total",
                        "memory-ledger snapshots taken").inc()

    def top_arrays(self, k: "int | None" = None) -> list:
        """The K largest live arrays, freshly attributed: [{nbytes,
        shape, dtype, region}] — the OOM bundle's "who is biggest"."""
        ids = self._region_ids()
        rows = []
        for a in jax.live_arrays():
            rows.append({
                "nbytes": int(getattr(a, "nbytes", 0) or 0),
                "shape": list(getattr(a, "shape", ()) or ()),
                "dtype": str(getattr(a, "dtype", "?")),
                "region": ids.get(id(a), REGION_UNATTRIBUTED),
            })
        rows.sort(key=lambda r: -r["nbytes"])
        return rows[:(k or self.top_k)]

    def timeline_copy(self) -> list:
        """A consistent copy of the timeline ring. Readers on OTHER
        threads (diag handlers, the fleet shard writer, the OOM dump)
        must use this: iterating the deque raw races the training
        thread's append (RuntimeError: deque mutated during
        iteration)."""
        with self._snap_lock:
            return list(self.timeline)

    def region_bytes(self) -> "dict | None":
        """The latest snapshot's {regions, total_bytes, n_arrays, step}
        — what a fleet shard carries per publish."""
        if not self.timeline:
            return None
        s = self.timeline[-1]
        return {"regions": dict(s["regions"]),
                "total_bytes": s["total_bytes"],
                "n_arrays": s["n_arrays"], "step": s["step"]}

    # -- step plumbing -----------------------------------------------------
    def _on_step(self, _seconds):
        """observe.add_step_listener hook: fires at the END of
        record_step, after the model committed the step's new state
        buffers, so params/opt attribute to arrays that are live."""
        if not self.enabled:
            return
        self.steps_seen += 1
        if self.steps_seen % self.interval_steps:
            return
        self.snapshot(step=self.steps_seen)
        if self.leak is not None:
            # locked copy: a concurrent sampler thread's append must
            # not blow up the window iteration
            self.leak.check(self.timeline_copy(), step=self.steps_seen)

    def _on_span(self, path, _seconds, _attrs):
        if not self.enabled:
            return
        if path.rsplit("/", 1)[-1] in SNAPSHOT_SPAN_LEAVES:
            self.snapshot()

    def _sample_loop(self, interval_s: float):
        while not self._stop.wait(interval_s):
            try:
                if self.enabled:
                    self.snapshot()
            except Exception:
                pass  # sampling must never kill the thread

    def close(self):
        self._stop.set()
        t = self._thread
        self._thread = None
        if t is not None:
            t.join(timeout=5.0)
        with _lock:
            if self in _samplers:
                _samplers.remove(self)


# ---- module singleton ------------------------------------------------------

_ledger: "MemoryLedger | None" = None
_samplers: "list[MemoryLedger]" = []  # ledgers with a live sampler thread


def install_ledger(**kwargs) -> MemoryLedger:
    """Install (or return) the process MemoryLedger and wire it to the
    span stream: every `model.step` (and `serving.decode`) exit takes a
    snapshot. Idempotent — a second call returns the running ledger."""
    global _ledger
    with _lock:
        if _ledger is not None:
            return _ledger
        _ledger = MemoryLedger(**kwargs)
        observe.add_step_listener(_ledger._on_step)
        observe.add_span_listener(_ledger._on_span)
        return _ledger


def uninstall_ledger():
    """Remove the ledger: span listener detached, sampler thread joined.
    Birth-site providers stay registered (they belong to the objects,
    not the ledger); `reset()` clears those too."""
    global _ledger
    with _lock:
        led = _ledger
        _ledger = None
    if led is not None:
        observe.remove_step_listener(led._on_step)
        observe.remove_span_listener(led._on_span)
        led.close()


def get_ledger() -> "MemoryLedger | None":
    return _ledger


def reset():
    """Full teardown (the conftest contract): ledger uninstalled,
    every sampler thread joined (including a raw MemoryLedger a test
    built without install_ledger), every provider and transient note
    dropped, the record_hbm fallback cache invalidated."""
    uninstall_ledger()
    with _lock:
        stray = list(_samplers)
    for led in stray:
        led.close()
    with _lock:
        _providers.clear()
        _transients.clear()
    _fallback_cache[0] = float("-inf")
    _fallback_cache[1] = 0


# ---- OOM forensics ---------------------------------------------------------

def is_resource_exhausted(exc) -> bool:
    """True for the XLA allocator's RESOURCE_EXHAUSTED XlaRuntimeError
    (matched structurally — jaxlib moves the class between releases)."""
    if exc is None:
        return False
    names = {c.__name__ for c in type(exc).__mro__}
    if "XlaRuntimeError" not in names:
        return False
    return "RESOURCE_EXHAUSTED" in str(exc)


def dump_oom_bundle(exc=None, key=None, out_dir=None,
                    ledger: "MemoryLedger | None" = None) -> str:
    """Write the OOM post-mortem bundle (JSONL, `flight_oom_step<N>`,
    round-tripped by `health.load_flight_bundle`): a header carrying
    the region breakdown, the top-K largest live arrays, the fit
    estimate and the executable manifest, then the memory timeline as
    `flight_step` lines and the recent EventLog tail."""
    led = ledger if ledger is not None else _ledger
    one_shot = led is None
    if one_shot:
        led = MemoryLedger(timeline=1, leak=None)
    snap = led.snapshot()
    top = led.top_arrays()
    execs = None
    try:
        from . import introspect
        execs = introspect.executable_manifest()[-8:] or None
    except Exception:
        pass
    fit = None
    try:
        fit = estimate_fit()
    except Exception:
        pass
    d = out_dir or led.out_dir
    if d is None:
        # default to the directory /flightz indexes (the active
        # monitor's flight recorder), so an OOM post-mortem shows up
        # next to the anomaly bundles instead of landing in an
        # unindexed CWD
        from . import health
        mon = health.active_monitor()
        d = getattr(getattr(mon, "recorder", None), "out_dir", None) \
            or "."
    os.makedirs(d, exist_ok=True)
    c = observe.get_registry().get("singa_steps_total")
    step = int(c.value()) if c is not None else led.steps_seen
    path = os.path.join(d, f"flight_oom_step{step}.jsonl")
    k = 1
    while os.path.exists(path):
        # a second OOM at the same step count (a serving process that
        # catches and carries on) must not overwrite the first
        # post-mortem
        k += 1
        path = os.path.join(d, f"flight_oom_step{step}_{k}.jsonl")
    tail = list(observe.get_registry().recent)[-64:]
    timeline = led.timeline_copy()
    header = {
        "kind": "flight_header", "ts": round(time.time(), 6),
        "reason": "oom", "step": step,
        "n_steps": len(timeline), "n_events": len(tail),
        "oom": {
            "error": str(exc)[:2000] if exc is not None else None,
            "executable_key": key,
            "regions": dict(snap["regions"]),
            "total_bytes": snap["total_bytes"],
            "n_arrays": snap["n_arrays"],
            "top_arrays": top,
            "fit": fit,
        },
        "executables": execs,
    }
    with open(path, "w", encoding="utf-8") as f:
        f.write(json.dumps(header, separators=(",", ":"),
                           default=str) + "\n")
        for s in timeline:
            f.write(json.dumps({"kind": "flight_step", **s},
                               separators=(",", ":"), default=str) + "\n")
        for ev in tail:
            f.write(json.dumps({"kind": "flight_event", "event": ev},
                               separators=(",", ":"), default=str) + "\n")
    if one_shot:
        led.close()
    return path


def handle_oom(exc, key=None, out_dir=None) -> "str | None":
    """The dispatch-site hook (model step, serving AOT executors):
    dump the forensics bundle for a resource-exhausted error and
    return its path. Never raises — the original OOM must propagate,
    not a forensics failure."""
    if not is_resource_exhausted(exc):
        return None
    try:
        path = dump_oom_bundle(exc=exc, key=key, out_dir=out_dir)
        # counted only once the bundle actually exists on disk — an
        # unwritable out_dir must not advance the counter
        observe.counter("singa_mem_oom_dumps_total",
                        "OOM forensics bundles written").inc()
        observe.get_registry().emit(
            {"kind": "mem", "event": "oom", "bundle": path,
             "executable_key": key, "error": str(exc)[:500]})
        return path
    except Exception:
        return None


# ---- pre-flight fit --------------------------------------------------------

def device_limit_bytes(device=None) -> "int | None":
    """The device HBM limit: allocator stats when the backend has them,
    else the `SINGA_TPU_HBM_LIMIT_BYTES` override (how the CPU tier
    tests the fit math), else None (unknown)."""
    jd = getattr(device, "jax_device", device)
    if jd is None:
        try:
            jd = jax.devices()[0]
        except Exception:
            jd = None
    stats = None
    if jd is not None:
        try:
            stats = jd.memory_stats()
        except Exception:
            stats = None
    if stats and stats.get("bytes_limit"):
        return int(stats["bytes_limit"])
    env = os.environ.get("SINGA_TPU_HBM_LIMIT_BYTES")
    if env:
        try:
            return int(float(env))
        except ValueError:
            return None
    return None


def estimate_fit(model=None, batch=None, device=None) -> dict:
    """Pre-flight "does this training step fit" estimate: introspect's
    static per-executable analysis (arguments/outputs/temps/generated
    code of the compiled step) combined with the ledger's measured
    param + optimizer bytes, against the device limit. `fits` is None
    when no limit is known (CPU without the env override)."""
    from . import introspect
    params_b = opt_b = 0
    if model is not None:
        try:
            params_b = sum(int(getattr(t.data, "nbytes", 0) or 0)
                           for t in model.get_params().values())
        except Exception:
            params_b = 0
        o = getattr(model, "_optimizer", None)
        if o is not None:
            try:
                opt_b = sum(int(getattr(a, "nbytes", 0) or 0)
                            for a in o.state_arrays())
            except Exception:
                opt_b = 0
    elif _ledger is not None and _ledger.timeline:
        regions = _ledger.timeline[-1]["regions"]
        params_b = int(regions.get(REGION_PARAMS, 0))
        opt_b = int(regions.get(REGION_OPT_STATE, 0))
    batch_b = sum(int(getattr(a, "nbytes", 0) or 0)
                  for a in _iter_arrays(batch)) if batch is not None else 0
    step = introspect.last_build("step")
    mem = dict((step or {}).get("memory") or {})
    exec_total = sum(int(v) for v in mem.values())
    # the executable's own requirement: arguments (which include the
    # donated params/opt slots and the batch) + outputs + temps +
    # generated code. last_build("step") is PROCESS-GLOBAL, so when a
    # DIFFERENT (larger) model is being sized the stale executable must
    # not under-report: the measured params+opt+batch floor always
    # applies, and `source` says which side won.
    floor = params_b + opt_b + batch_b
    estimated = max(exec_total, floor)
    dev = device if device is not None \
        else getattr(model, "_device", None)
    limit = device_limit_bytes(dev)
    rep = {
        "params_bytes": params_b,
        "opt_state_bytes": opt_b,
        "batch_bytes": batch_b,
        "exec_arguments_bytes": mem.get("arguments"),
        "exec_outputs_bytes": mem.get("outputs"),
        "exec_temps_bytes": mem.get("temps"),
        "exec_generated_code_bytes": mem.get("generated_code"),
        "estimated_peak_bytes": int(estimated),
        "limit_bytes": limit,
        "fits": (estimated <= limit) if limit else None,
        "headroom_frac": round(1.0 - estimated / limit, 4)
        if limit else None,
        "source": "executable" if exec_total >= floor and exec_total
        else "ledger",
    }
    return rep


# ---- /memz reports ---------------------------------------------------------

def _mb(b) -> str:
    return f"{(b or 0) / 1e6:10.2f} MB"


def memz_json(timeline_tail: int = 64, include_top: bool = True) -> dict:
    """The /memz?json=1 body: latest breakdown, timeline, leak state,
    the static introspect HBM view, and the fit estimate. The text
    view passes include_top=False — top_arrays costs a fresh
    live-array attribution pass it never renders."""
    from . import introspect
    led = _ledger
    out: dict = {"installed": led is not None}
    if led is None:
        return out
    if not led.timeline:
        led.snapshot()
    tl = led.timeline_copy()  # diag handler thread vs training appends
    s = tl[-1]
    out.update({
        "regions": dict(s["regions"]),
        "counts": dict(s["counts"]),
        "total_bytes": s["total_bytes"],
        "n_arrays": s["n_arrays"],
        "step": s["step"],
        "timeline": [{"step": t["step"], "ts": t["ts"],
                      "total_bytes": t["total_bytes"],
                      "regions": dict(t["regions"])}
                     for t in tl[-timeline_tail:]],
    })
    if include_top:
        out["top_arrays"] = led.top_arrays(8)
    if led.leak is not None:
        out["leak"] = {
            "slope_bytes_per_step": round(led.leak.slope, 1),
            "min_slope_bytes": led.leak.min_slope_bytes,
            "verdicts": list(led.leak.verdicts),
        }
    step = introspect.last_build("step")
    out["static_hbm"] = dict((step or {}).get("memory") or {})
    try:
        out["fit"] = estimate_fit()
    except Exception:
        out["fit"] = None
    return out


def memz_report() -> str:
    """Text block for /memz (and /statusz-style reading): the region
    breakdown table, the reconciliation line, the static introspect
    HBM view side-by-side, the leak state and the timeline tail."""
    rep = memz_json(timeline_tail=8, include_top=False)
    lines = ["== memory =="]
    if not rep.get("installed"):
        lines.append("no MemoryLedger installed "
                     "(singa_tpu.memory.install_ledger())")
        return "\n".join(lines)
    lines.append(f"{'region':<16} {'bytes':>14} {'MB':>13} {'arrays':>7}")
    for region in MEM_REGIONS:
        b = rep["regions"].get(region, 0)
        lines.append(f"{region:<16} {b:>14}{_mb(b)} "
                     f"{rep['counts'].get(region, 0):>7}")
    lines.append(f"{'TOTAL':<16} {rep['total_bytes']:>14}"
                 f"{_mb(rep['total_bytes'])} {rep['n_arrays']:>7}")
    region_sum = sum(rep["regions"].values())
    ok = "OK" if region_sum == rep["total_bytes"] else "BROKEN"
    lines.append(f"reconciliation: region sum {region_sum} == live "
                 f"total {rep['total_bytes']} ({ok})")
    static = rep.get("static_hbm") or {}
    if static:
        lines.append("static estimate (introspect, step executable): "
                     + " | ".join(f"{k} {v / 1e6:.2f} MB"
                                  for k, v in sorted(static.items())))
        live_po = (rep["regions"].get(REGION_PARAMS, 0)
                   + rep["regions"].get(REGION_OPT_STATE, 0))
        est_args = static.get("arguments")
        if est_args:
            drift = (live_po - est_args) / est_args * 100.0
            lines.append(f"estimate-vs-actual: live params+opt "
                         f"{live_po / 1e6:.2f} MB vs executable "
                         f"arguments {est_args / 1e6:.2f} MB "
                         f"({drift:+.1f}% drift)")
    else:
        lines.append("static estimate: none (no step executable built)")
    leak = rep.get("leak")
    if leak is not None:
        lines.append(f"leak: slope {leak['slope_bytes_per_step']} B/step "
                     f"(threshold {leak['min_slope_bytes']:g}), "
                     f"{len(leak['verdicts'])} verdict(s)")
        for v in leak["verdicts"][-3:]:
            lines.append(f"  step {v['step']}: suspect "
                         f"{v['suspect_region']} "
                         f"(+{v['suspect_delta_bytes']} B over "
                         f"{v['window']} steps)")
    fit = rep.get("fit")
    if fit:
        lim = fit.get("limit_bytes")
        lines.append(
            f"fit: estimated peak {fit['estimated_peak_bytes'] / 1e6:.2f}"
            f" MB vs limit "
            + (f"{lim / 1e6:.2f} MB -> "
               f"{'fits' if fit['fits'] else 'DOES NOT FIT'} "
               f"(headroom {fit['headroom_frac'] * 100.0:.1f}%)"
               if lim else "unknown (no allocator stats; set "
               "SINGA_TPU_HBM_LIMIT_BYTES)"))
    lines.append("timeline (newest last): " + "  ".join(
        f"s{t['step']}:{t['total_bytes'] / 1e6:.1f}MB"
        for t in rep.get("timeline", [])))
    return "\n".join(lines)


__all__ = [
    "MEM_REGIONS", "MemoryLedger", "LeakDetector",
    "install_ledger", "uninstall_ledger", "get_ledger", "reset",
    "register_provider", "unregister_provider", "region_has_provider",
    "note_arrays",
    "track_model", "track_optimizer", "track_prefetcher", "untrack",
    "total_live_bytes", "hbm_fallback_bytes",
    "is_resource_exhausted", "dump_oom_bundle",
    "handle_oom", "estimate_fit", "device_limit_bytes",
    "memz_report", "memz_json", "SNAPSHOT_SPAN_LEAVES", "OOM_TOP_K",
]
