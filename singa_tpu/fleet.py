"""Fleet observability: cross-process telemetry, merged into one surface.

PRs 1-6 made a single process fully legible — metrics registry, spans,
compile blame, goodput ledger, live /statusz — and made multi-process
training survivable, but every telemetry surface stayed strictly
per-process: in the MULTICHIP/kill-resume harnesses each worker has its
own registry, its own diag server, its own flight recorder, and nothing
can answer "which host is slow?" or "what did the fleet do at step N?".
This module is the cross-process layer over the `jax.distributed`
topology (`distributed.topology()` / `host_label()`):

  - **ShardWriter** (every worker): periodically serializes the process's
    telemetry — metrics snapshot, goodput buckets, health verdict, and
    the recent span-record ring (`observe.enable_span_records`) — to a
    shared spool directory as `fleet_dir/worker_<pid>.shard.jsonl`.
    Each publish rewrites the whole file via tmp + atomic `os.replace`
    with a monotonic sequence number, so a reader never sees a torn
    shard and can tell a fresh publish from a stalled one. The shard
    header carries a paired `(time.time(), time.perf_counter())` clock
    sample — the handshake the aggregator uses to align every worker's
    monotonic span stamps onto one wall-clock timeline.

  - **FleetAggregator** (the coordinator): scans the spool, merges
    shards into fleet-level rollups — counters summed, histograms merged
    bucket-wise, gauges kept per-host with min/max/mean — and tracks
    per-worker staleness: a worker whose shard stops aging forward is
    flagged dead-or-wedged after `stale_after_s`.

  - **Straggler detector**: each worker's per-step (`model.step` span)
    and per-collective (`singa_comm_host_seconds` stamps from
    parallel/communicator.py) timings are scored as deviation from the
    fleet median — `score = (host - median) / median`, floored at 0 —
    exported as `singa_fleet_straggler_score{host=...}`. A host above
    `threshold` for `sustain` consecutive polls is a SUSTAINED
    straggler: the verdict feeds the active `health.HealthMonitor`
    (its warn/halt policy applies, `note_external`) and, under the halt
    policy, `check_straggler_halt()` raises `FleetStragglerError`
    (a HealthError) out of `resilience.TrainController`'s loop so the
    elastic restart can exclude the slow host (`report["exclude_hosts"]`).

  - **Merged trace export**: every worker's span records (name, start,
    duration, tid, pid) are aligned via the per-worker clock handshake
    and emitted as one Chrome Trace Event Format JSON
    (`export_trace(path)`) — loads in Perfetto with one track per host,
    the first artifact where a cross-host stall is *visible* rather
    than inferred.

  - Diag endpoints: the coordinator's existing `diag.DiagServer` serves
    `/fleetz` (per-host step rate, goodput ratio, straggler scores,
    shard staleness) and `/fleetz/trace` (the merged trace, on demand).

CLI: `python -m singa_tpu.fleet --ab --out FLEET_r01.json` runs the
MULTICHIP-style subprocess A/B — N workers, one with a FaultPlan-injected
delay on its collectives (`fault_point("comm.collective")`), a
coordinator that must detect the straggler within K steps from /fleetz
and export a schema-valid merged trace showing the injected gap.
"""

from __future__ import annotations

import json
import os
import shutil
import statistics
import tempfile
import threading
import time

from . import distributed, health, observe, slo

SHARD_VERSION = 1
SHARD_SUFFIX = ".shard.jsonl"

#: span-record leaf names the straggler detector treats as one train step
STEP_SPAN_LEAF = "model.step"

#: how many of a worker's most recent step/collective samples feed its
#: straggler signal (older samples describe a previous regime)
_SIGNAL_WINDOW = 32

#: per-worker cap on span records retained for the merged trace
_TRACE_SPANS_PER_WORKER = 20_000


class FleetStragglerError(health.HealthError):
    """Raised by `check_straggler_halt` once a sustained straggler
    verdict lands under the halt policy. A HealthError on purpose:
    `resilience.TrainController` already routes HealthError through its
    save-then-stop path (final checkpoint, manifest status "halt") and
    attaches the run report — this adds `.hosts`, the slow host(s) an
    elastic restart should exclude."""

    def __init__(self, msg, hosts=(), score=None):
        super().__init__(msg)
        self.hosts = tuple(hosts)
        self.score = score


# ---- metrics ---------------------------------------------------------------

def _writer_metrics():
    # observe.counter/gauge spelled out so the static lint sees them
    return {
        "publishes": observe.counter(
            "singa_fleet_shard_publish_total",
            "telemetry shard publishes by this worker"),
        "errors": observe.counter(
            "singa_fleet_shard_publish_errors_total",
            "telemetry shard publishes that failed"),
        "seq": observe.gauge(
            "singa_fleet_shard_seq_last",
            "sequence number of this worker's last published shard"),
    }


def _agg_metrics():
    return {
        "polls": observe.counter(
            "singa_fleet_polls_total",
            "aggregator spool scans"),
        "workers": observe.gauge(
            "singa_fleet_workers",
            "worker shards the aggregator currently tracks"),
        "stale": observe.gauge(
            "singa_fleet_workers_stale",
            "tracked workers whose shard stopped aging forward"),
        "score": observe.gauge(
            "singa_fleet_straggler_score",
            "per-host deviation from the fleet-median step/collective "
            "time ((host - median)/median, floored at 0)"),
        "age": observe.gauge(
            "singa_fleet_shard_age_seconds",
            "seconds since each worker's last shard publish"),
        "seq": observe.gauge(
            "singa_fleet_shard_seq",
            "per-host sequence number of the last shard seen"),
        "rate": observe.gauge(
            "singa_fleet_step_rate",
            "per-host train steps per second (between shard publishes)"),
        "goodput": observe.gauge(
            "singa_fleet_goodput_ratio",
            "per-host productive share of wall time, from each "
            "worker's goodput snapshot"),
        "mem": observe.gauge(
            "singa_fleet_mem_bytes",
            "per-host total live device bytes, from each worker's "
            "memory-ledger region snapshot"),
        "sustained": observe.counter(
            "singa_fleet_straggler_sustained_total",
            "sustained-straggler verdicts by host"),
        "serve_rps": observe.gauge(
            "singa_fleet_serve_rps",
            "per-host serving-engine terminal requests per second, "
            "from each worker's fleet_serve snapshot"),
        "slo_att": observe.gauge(
            "singa_fleet_slo_attainment_pct",
            "per-host worst-objective SLO attainment percent, from "
            "each worker's fleet_serve snapshot"),
    }


# ---- shard writing ---------------------------------------------------------

class ShardWriter:
    """Publishes this process's telemetry to `fleet_dir` as an atomic
    JSONL shard with a monotonic `seq`.

    `interval_s > 0` starts a daemon publisher thread
    (`singa-fleet-shard-<pid>`); `interval_s = 0` means manual-only
    (`publish()`), which tests use. `fleet_dir=None` creates a temp
    spool dir (owned by this module; `fleet.uninstall()` removes it).
    Enables the observe span-record ring so recent spans and collective
    stamps ride along in every shard.
    """

    def __init__(self, fleet_dir: "str | None" = None,
                 interval_s: float = 0.5, host: "str | None" = None,
                 name: "str | None" = None, span_capacity: int = 4096):
        if fleet_dir is None:
            fleet_dir = tempfile.mkdtemp(prefix="singa_fleet_")
            _owned_dirs.append(fleet_dir)
        self.fleet_dir = os.path.abspath(fleet_dir)
        os.makedirs(self.fleet_dir, exist_ok=True)
        self.host = host or distributed.host_label()
        self.pid = os.getpid()
        self.interval_s = float(interval_s)
        base = name or f"worker_{self.pid}"
        self.path = os.path.join(self.fleet_dir, base + SHARD_SUFFIX)
        self.seq = 0
        self.started_ts = time.time()
        self._plock = threading.Lock()
        self._stop = threading.Event()
        self._thread = None
        observe.enable_span_records(span_capacity)
        _writers.append(self)
        if self.interval_s > 0:
            self._thread = threading.Thread(
                target=self._loop, daemon=True,
                name=f"singa-fleet-shard-{self.pid}")
            self._thread.start()

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.publish()
            except Exception:
                # a broken publish must never kill the publisher (the
                # next tick retries); it is counted, not raised
                try:
                    _writer_metrics()["errors"].inc()
                except Exception:
                    pass

    def _snapshot_lines(self):
        header = {
            "kind": "fleet_shard_header", "version": SHARD_VERSION,
            "seq": self.seq, "host": self.host, "pid": self.pid,
            # the clock handshake: one paired (epoch, monotonic) sample
            # per publish — the aggregator maps this worker's span
            # stamps onto the shared wall clock via ts - perf
            "ts": round(time.time(), 6),
            "perf": round(time.perf_counter(), 7),
            "started_ts": round(self.started_ts, 6),
            "steps": self._steps(),
        }
        lines = [header,
                 {"kind": "fleet_metrics",
                  "metrics": observe.get_registry().snapshot()}]
        gp = None
        try:
            from . import goodput
            tracker = goodput.get_tracker()
            if tracker is not None:
                gp = tracker.snapshot()
        except Exception:
            gp = None
        lines.append({"kind": "fleet_goodput", "goodput": gp})
        mon = health.active_monitor()
        lines.append({"kind": "fleet_health",
                      "verdict": mon.verdict() if mon is not None
                      else None})
        mem = None
        try:
            from . import memory
            led = memory.get_ledger()
            if led is not None:
                mem = led.region_bytes()  # per-host region snapshot
        except Exception:
            mem = None
        lines.append({"kind": "fleet_mem", "mem": mem})
        hang = None
        try:
            # the watchdog's hang verdict rides every shard: this is
            # how a WEDGED worker (one that cannot step, let alone be
            # merely slow) becomes visible to the rest of the fleet —
            # the aggregator escalates a peer's abort-stage verdict
            # fleet-wide (check_straggler_halt)
            from . import watchdog
            hang = watchdog.hang_report()
        except Exception:
            hang = None
        lines.append({"kind": "fleet_hang", "hang": hang})
        serve = None
        try:
            # the serving view (singa_tpu.slo): live engine occupancy/
            # queue/RPS/TTFT + SLO attainment, plus the recent request
            # timelines and decode-sync records the merged trace needs
            # to show requests flowing through this replica
            serve = slo.fleet_serve_snapshot()
        except Exception:
            serve = None
        lines.append({"kind": "fleet_serve", "serve": serve})
        cap = None
        try:
            # this replica's own headroom row (singa_tpu.capacity):
            # derived from the SAME serve signals the line above
            # publishes, so the coordinator's headroom column
            # reconciles against the shard by construction — plus the
            # local shadow scaler's last decision when one is installed
            from . import capacity
            cap = capacity.fleet_capacity_snapshot()
        except Exception:
            cap = None
        lines.append({"kind": "fleet_capacity", "capacity": cap})
        aud = None
        try:
            # this replica's param-integrity fingerprint
            # (singa_tpu.audit): the aggregator majority-votes these
            # across replicas serving the same model and flags a
            # dissenter (silent data corruption) with the first
            # diverging layer-group named
            from . import audit
            aud = audit.fleet_audit_snapshot()
        except Exception:
            aud = None
        lines.append({"kind": "fleet_audit", "audit": aud})
        reg = None
        try:
            # this replica's regression-detector rollup
            # (singa_tpu.regress): the aggregator's localization vote
            # over these lines splits one-host-regressed (hardware
            # suspect) from fleet-wide-regressed (software)
            from . import regress
            reg = regress.fleet_regress_snapshot()
        except Exception:
            reg = None
        lines.append({"kind": "fleet_regress", "regress": reg})
        for rec in observe.span_records():
            lines.append({"kind": "fleet_span", "name": rec["name"],
                          "t0": rec["t0"], "dur": rec["dur"],
                          "tid": rec["tid"],
                          "span_kind": rec.get("kind", "span")})
        return lines

    @staticmethod
    def _steps() -> int:
        c = observe.get_registry().get("singa_steps_total")
        return int(c.value()) if c is not None else 0

    def publish(self) -> int:
        """Serialize one shard and atomically replace the previous one.
        Returns the published sequence number. The watchdog arms its
        `fleet_publish` deadline over the write (a wedged spool — dead
        NFS, full disk blocking forever — must not silently turn this
        worker invisible to the fleet); `fleet.publish` is the
        deterministic FaultPlan hook."""
        from . import resilience, watchdog
        with self._plock, watchdog.guard("fleet_publish"):
            resilience.fault_point("fleet.publish")
            self.seq += 1
            lines = self._snapshot_lines()
            tmp = self.path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                for rec in lines:
                    f.write(json.dumps(rec, separators=(",", ":"),
                                       default=str) + "\n")
                f.flush()
            os.replace(tmp, self.path)
            m = _writer_metrics()
            m["publishes"].inc()
            m["seq"].set(float(self.seq))
            return self.seq

    def close(self, final_publish: bool = True):
        """Stop the publisher thread (joined) and optionally publish one
        last shard so the spool holds this worker's final state."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        if final_publish:
            try:
                self.publish()
            except Exception:
                pass
        if self in _writers:
            _writers.remove(self)


def read_shard(path: str) -> "dict | None":
    """Parse one shard file back into {"header", "metrics", "goodput",
    "health", "spans"} — None when the file is missing or carries no
    valid header (an interrupted worker start; atomic replace means a
    PUBLISHED shard is never torn)."""
    rows = observe.EventLog.read(path)
    header = next((r for r in rows
                   if r.get("kind") == "fleet_shard_header"), None)
    if header is None or not isinstance(header.get("seq"), int):
        return None
    return {
        "header": header,
        "metrics": next((r.get("metrics") for r in rows
                         if r.get("kind") == "fleet_metrics"), None) or {},
        "goodput": next((r.get("goodput") for r in rows
                         if r.get("kind") == "fleet_goodput"), None),
        "health": next((r.get("verdict") for r in rows
                        if r.get("kind") == "fleet_health"), None),
        "mem": next((r.get("mem") for r in rows
                     if r.get("kind") == "fleet_mem"), None),
        "hang": next((r.get("hang") for r in rows
                      if r.get("kind") == "fleet_hang"), None),
        "serve": next((r.get("serve") for r in rows
                       if r.get("kind") == "fleet_serve"), None),
        "capacity": next((r.get("capacity") for r in rows
                          if r.get("kind") == "fleet_capacity"), None),
        "audit": next((r.get("audit") for r in rows
                       if r.get("kind") == "fleet_audit"), None),
        "regress": next((r.get("regress") for r in rows
                         if r.get("kind") == "fleet_regress"), None),
        "spans": [r for r in rows if r.get("kind") == "fleet_span"],
    }


# ---- merging ---------------------------------------------------------------

def merge_metric_snapshots(snaps: dict) -> dict:
    """Merge per-host registry snapshots ({host: snapshot}) into fleet
    rollups: counters and histograms are SUMMED across hosts (bucket-wise
    for histograms — cumulative counts sum to cumulative counts), gauges
    are kept per-host and summarized as min/max/mean. Label sets within
    a metric merge by their label key."""
    merged = {}
    for hostname, snap in sorted(snaps.items()):
        for name, m in (snap or {}).items():
            kind = m.get("type")
            out = merged.setdefault(name, {"type": kind, "series": {}})
            if out["type"] != kind:
                continue  # conflicting types across hosts: first wins
            for s in m.get("samples", []):
                key = tuple(sorted((s.get("labels") or {}).items()))
                row = out["series"].setdefault(
                    key, {"labels": dict(key)})
                if kind == "histogram":
                    row["count"] = row.get("count", 0) + s.get("count", 0)
                    row["sum"] = row.get("sum", 0.0) + s.get("sum", 0.0)
                    buckets = row.setdefault("buckets", {})
                    for ub, c in (s.get("buckets") or {}).items():
                        buckets[ub] = buckets.get(ub, 0) + c
                elif kind == "counter":
                    row["value"] = row.get("value", 0.0) + s.get("value",
                                                                 0.0)
                else:  # gauge (and anything unknown): per-host detail
                    per = row.setdefault("per_host", {})
                    per[hostname] = s.get("value", 0.0)
                    vals = list(per.values())
                    row["min"] = min(vals)
                    row["max"] = max(vals)
                    row["mean"] = sum(vals) / len(vals)
    return merged


# ---- the aggregator --------------------------------------------------------

class _WorkerState:
    __slots__ = ("path", "host", "pid", "seq", "ts", "perf", "steps",
                 "started_ts", "metrics", "goodput", "health", "mem",
                 "hang", "serve", "capacity", "audit", "regress",
                 "spans",
                 "prev_ts", "prev_steps", "step_rate", "over_since")

    def __init__(self, path):
        self.path = path
        self.host = None
        self.pid = None
        self.seq = -1
        self.ts = 0.0
        self.perf = 0.0
        self.steps = 0
        self.started_ts = 0.0
        self.metrics = {}
        self.goodput = None
        self.health = None
        self.mem = None   # per-host memory-ledger region snapshot
        self.hang = None  # per-host watchdog hang verdict (sticky)
        self.serve = None  # per-host serving snapshot (slo.fleet_serve)
        self.capacity = None  # per-host headroom row (fleet_capacity)
        self.audit = None  # per-host param fingerprint (fleet_audit)
        self.regress = None  # per-host detector rollup (fleet_regress)
        self.spans = {}   # (tid, t0, name) -> span rec, insertion-ordered
        self.prev_ts = None
        self.prev_steps = 0
        self.step_rate = 0.0
        self.over_since = 0  # consecutive polls above the threshold

    @property
    def clock_offset(self) -> float:
        """epoch seconds corresponding to this worker's perf_counter 0 —
        the handshake: ts and perf were sampled together at publish."""
        return self.ts - self.perf


class FleetAggregator:
    """Coordinator-side merge of the spool directory's worker shards.

    `poll()` re-scans the spool, updates per-worker state, recomputes
    straggler scores and exports the `singa_fleet_*` gauges; `rollup()`
    returns the last poll's fleet-level view. `policy` overrides the
    active HealthMonitor's policy for the sustained-straggler verdict
    (None = inherit the monitor's, default "warn"); under "halt" the
    verdict is held sticky for `check_straggler_halt()` to raise from
    the training loop.
    """

    def __init__(self, fleet_dir: str, stale_after_s: float = 5.0,
                 threshold: float = 0.5, sustain: int = 3,
                 policy: "str | None" = None,
                 poll_interval_s: float = 0.5,
                 background_poll: bool = False):
        self.fleet_dir = os.path.abspath(fleet_dir)
        self.stale_after_s = float(stale_after_s)
        self.threshold = float(threshold)
        self.sustain = int(sustain)
        if policy is not None and policy not in health.POLICIES:
            raise ValueError(
                f"policy {policy!r} not in {health.POLICIES}")
        self.policy = policy
        self.poll_interval_s = float(poll_interval_s)
        self._lock = threading.Lock()
        self._workers: "dict[str, _WorkerState]" = {}
        self._scores: "dict[str, float]" = {}
        self._stale: "dict[str, float]" = {}  # host -> age seconds
        self._halt: "dict | None" = None
        self._sustained: "set[str]" = set()
        # hang escalation: a peer's abort-stage watchdog verdict, held
        # sticky until the training loop consumes it (take_peer_hang).
        # `_hang_seen` de-duplicates by (host, verdict id) so one hang
        # episode triggers exactly ONE coordinated abort-and-restore.
        self._peer_hang: "dict | None" = None
        self._hang_seen: "set[tuple]" = set()
        # fingerprint vote: host -> dissent info while the host's
        # param fingerprint disagrees with the fleet majority;
        # `_audit_seen` de-duplicates the once-per-episode emit by
        # (host, fingerprint) so a persisting corruption logs once but
        # keeps feeding the observatory's streak every poll
        self._audit_dissent: "dict[str, dict]" = {}
        self._audit_seen: "set[tuple]" = set()
        self._last_poll = 0.0
        self.started_mono = time.monotonic()
        self._poll_stop = threading.Event()
        self._poll_thread = None
        if background_poll:
            self.start_polling()

    # -- polling -----------------------------------------------------------
    def _scan(self):
        try:
            names = os.listdir(self.fleet_dir)
        except OSError:
            names = []
        paths = [os.path.join(self.fleet_dir, n) for n in sorted(names)
                 if n.endswith(SHARD_SUFFIX)]
        # a worker whose shard file was removed (spool GC, relaunch
        # cleanup) is forgotten — otherwise ghost incarnations inflate
        # worker counts and keep feeding frozen signals forever
        live = set(paths)
        for path in list(self._workers):
            if path not in live:
                del self._workers[path]
        for path in paths:
            shard = read_shard(path)
            if shard is None:
                continue
            h = shard["header"]
            w = self._workers.get(path)
            if w is None:
                w = self._workers[path] = _WorkerState(path)
            if h["seq"] < w.seq:
                # a restarted worker reusing the shard path starts seq
                # over: RESET the state and accept the new incarnation
                # (skipping it would drop the restart's telemetry until
                # its seq caught up with the dead one's)
                w = self._workers[path] = _WorkerState(path)
            fresh = h["seq"] > w.seq
            if fresh:
                w.prev_ts, w.prev_steps = w.ts or None, w.steps
            w.seq = h["seq"]
            w.host = h.get("host") or f"pid{h.get('pid')}"
            w.pid = int(h.get("pid") or 0)
            w.ts = float(h.get("ts") or 0.0)
            w.perf = float(h.get("perf") or 0.0)
            w.steps = int(h.get("steps") or 0)
            w.started_ts = float(h.get("started_ts") or 0.0)
            w.metrics = shard["metrics"]
            w.goodput = shard["goodput"]
            w.health = shard["health"]
            w.mem = shard.get("mem")
            w.hang = shard.get("hang")
            w.serve = shard.get("serve")
            w.capacity = shard.get("capacity")
            w.audit = shard.get("audit")
            w.regress = shard.get("regress")
            if fresh and w.prev_ts and w.ts > w.prev_ts:
                w.step_rate = max(
                    0.0, (w.steps - w.prev_steps) / (w.ts - w.prev_ts))
            for rec in shard["spans"]:
                key = (rec.get("tid"), rec.get("t0"), rec.get("name"))
                w.spans[key] = rec
            if len(w.spans) > _TRACE_SPANS_PER_WORKER:
                drop = len(w.spans) - _TRACE_SPANS_PER_WORKER
                for key in list(w.spans)[:drop]:
                    del w.spans[key]

    @staticmethod
    def _signal(w: "_WorkerState", want_comm: bool) -> "float | None":
        """Mean duration of this worker's recent step or collective
        records, or None when it has published none yet."""
        durs = []
        for rec in reversed(list(w.spans.values())):
            if want_comm:
                hit = rec.get("span_kind") == "comm"
            else:
                name = rec.get("name") or ""
                hit = name.rsplit("/", 1)[-1] == STEP_SPAN_LEAF
            if hit:
                durs.append(float(rec.get("dur") or 0.0))
                if len(durs) >= _SIGNAL_WINDOW:
                    break
        return (sum(durs) / len(durs)) if durs else None

    def _score_locked(self):
        """(host -> straggler score): per signal (step time, collective
        time), deviation from the fleet median across hosts that have
        the signal; a host's score is the worst of its signals."""
        scores = {}
        for want_comm in (False, True):
            vals = {}
            freshest = {}
            for w in self._workers.values():
                if w.host is None:
                    continue
                v = self._signal(w, want_comm)
                if v is None:
                    continue
                # two shard files can carry the same host label (a dead
                # incarnation's file next to its relaunch): the NEWEST
                # publish owns the host's signal, regardless of scan
                # order
                if w.host not in freshest or w.ts > freshest[w.host]:
                    freshest[w.host] = w.ts
                    vals[w.host] = v
            if len(vals) < 2:
                continue  # a fleet of one has no median to deviate from
            med = statistics.median(vals.values())
            for hostname, v in vals.items():
                s = max(0.0, (v - med) / max(med, 1e-9))
                scores[hostname] = max(scores.get(hostname, 0.0), s)
        # hosts with no signal at all still appear (score 0) so /fleetz
        # lists every tracked worker
        for w in self._workers.values():
            if w.host is not None:
                scores.setdefault(w.host, 0.0)
        return scores

    def _resolved_policy(self) -> str:
        if self.policy is not None:
            return self.policy
        mon = health.active_monitor()
        if mon is not None and mon.policy == "halt":
            return "halt"
        return "warn"

    def _export_locked(self, now_epoch: float):
        """Export the singa_fleet_* gauges. Every host= label value here
        originates from distributed.host_label() on the worker that
        published the shard; the coordinator's own label (host_label())
        marks the local row in rollup()/fleet_report."""
        local = distributed.host_label()
        m = _agg_metrics()
        m["workers"].set(float(len(self._workers)))
        m["stale"].set(float(len(self._stale)))
        # oldest-first so a host label shared by a dead incarnation and
        # its relaunch gets the FRESHEST shard's values in the gauges
        for w in sorted(self._workers.values(), key=lambda w: w.ts):
            if w.host is None:
                continue
            m["age"].set(max(0.0, now_epoch - w.ts), host=w.host)
            m["seq"].set(float(w.seq), host=w.host)
            m["rate"].set(w.step_rate, host=w.host)
            if isinstance(w.goodput, dict):
                m["goodput"].set(
                    float(w.goodput.get("goodput_ratio") or 0.0),
                    host=w.host)
            if isinstance(w.mem, dict):
                m["mem"].set(float(w.mem.get("total_bytes") or 0.0),
                             host=w.host)
            if isinstance(w.serve, dict):
                m["serve_rps"].set(float(w.serve.get("rps") or 0.0),
                                   host=w.host)
                att = slo.serve_attainment_pct(w.serve)
                if att is not None:
                    m["slo_att"].set(att, host=w.host)
        for hostname, score in self._scores.items():
            m["score"].set(score, host=hostname)
        return local

    def _verdicts_locked(self):
        """Advance per-host sustained-straggler state; fire policy
        actions on the poll that crosses `sustain`."""
        fired = []
        for w in self._workers.values():
            if w.host is None:
                continue
            if self._scores.get(w.host, 0.0) > self.threshold:
                w.over_since += 1
            else:
                w.over_since = 0
                self._sustained.discard(w.host)
            if w.over_since >= self.sustain \
                    and w.host not in self._sustained:
                self._sustained.add(w.host)
                fired.append((w.host, self._scores.get(w.host, 0.0)))
        return fired

    def _apply_policy(self, fired):
        """Outside the lock: metric/emit/monitor plumbing for each new
        sustained verdict (host values originate from host_label() on
        the workers; see _export_locked)."""
        if not fired:
            return
        policy = self._resolved_policy()
        mon = health.active_monitor()
        # every hostname below was minted by distributed.host_label()
        # on the worker that published it; the coordinator's own label
        # tags the verdict's origin
        local = distributed.host_label()
        for hostname, score in fired:
            _agg_metrics()["sustained"].inc(host=hostname)
            observe.get_registry().emit(
                {"kind": "fleet", "event": "straggler_sustained",
                 "host": hostname, "coordinator": local,
                 "score": round(score, 4), "policy": policy})
            if mon is not None:
                try:
                    # pass the RESOLVED action: the aggregator's policy
                    # may override the monitor's, and /healthz must not
                    # claim a halt that never happened (or vice versa)
                    mon.note_external(
                        health.KIND_STRAGGLER,
                        detail={"host": hostname,
                                "score": round(score, 4)},
                        action="halt" if policy == "halt" else "warn")
                except Exception:
                    pass  # the monitor must not break the aggregator
            if policy == "halt" and self._halt is None:
                self._halt = {"host": hostname,
                              "score": round(score, 4),
                              "ts": round(time.time(), 6)}

    def _audit_vote_locked(self):
        """Majority-vote the param-integrity fingerprints (the
        fleet_audit shard line, singa_tpu.audit) across hosts serving
        the same model. A host whose fingerprint disagrees with a
        STRICT majority (> half of >= 3 voters — two replicas cannot
        outvote each other, and without a majority nobody is convicted)
        is a dissenter: silent data corruption, flagged with the first
        diverging layer-group named. Returns the dissent list for
        _apply_audit (outside the lock)."""
        fps = {}
        freshest = {}
        for w in self._workers.values():
            a = w.audit
            if w.host is None or not isinstance(a, dict):
                continue
            fp = a.get("fingerprint")
            if not fp:
                continue
            # newest publish owns a host's vote (dead incarnation's
            # file next to its relaunch — same rule as _score_locked)
            if w.host not in freshest or w.ts > freshest[w.host]:
                freshest[w.host] = w.ts
                try:
                    fps[w.host] = tuple(
                        (str(g), int(v)) for g, v in fp)
                except (TypeError, ValueError):
                    continue
        self._audit_dissent = {}
        if len(fps) < 3:
            return []
        counts = {}
        for fp in fps.values():
            counts[fp] = counts.get(fp, 0) + 1
        majority_fp, n = max(counts.items(), key=lambda kv: kv[1])
        if n <= len(fps) // 2:
            return []
        fired = []
        for hostname, fp in sorted(fps.items()):
            if fp == majority_fp:
                continue
            first = next(
                (g for (g, v), (_, mv) in zip(fp, majority_fp)
                 if v != mv), None)
            info = {"first_group": first, "voters": len(fps),
                    "majority": n}
            self._audit_dissent[hostname] = info
            fired.append((hostname, fp, info))
        return fired

    def _apply_audit(self, fired):
        """Outside the lock: feed each fingerprint dissenter into the
        audit observatory (which owns sustain + quarantine) — EVERY
        poll while the dissent persists, so the observatory's streak
        builds at poll cadence; the EventLog record and the
        no-observatory health-note fallback fire once per (host,
        fingerprint) episode."""
        if not fired:
            return
        from . import audit as audit_mod
        local = distributed.host_label()
        obs = audit_mod.get_observatory()
        mon = health.active_monitor()
        for hostname, fp, info in fired:
            key = (hostname, fp)
            new = key not in self._audit_seen
            if new:
                self._audit_seen.add(key)
                if observe.is_enabled():
                    observe.get_registry().emit(
                        {"kind": "audit",
                         "event": "fingerprint_dissent",
                         "host": hostname, "coordinator": local,
                         **info})
            detail = (f"fingerprint dissent: first diverging group "
                      f"{info['first_group']} "
                      f"({info['majority']}/{info['voters']} voters "
                      f"agree)")
            if obs is not None:
                obs.note(hostname, audit_mod.LEG_FINGERPRINT,
                         audit_mod.VERDICT_MISMATCH, detail=detail)
            elif new and mon is not None:
                try:
                    # a verdict is health state, not telemetry: even
                    # without an observatory the dissent must reach
                    # /healthz
                    mon.note_external(
                        health.KIND_DIVERGENCE,
                        detail={"host": hostname, **info},
                        action="warn")
                except Exception:
                    pass  # the monitor must not break the aggregator

    def audit_dissent(self) -> dict:
        """host -> dissent info for hosts currently outvoted on their
        param fingerprint (empty when the fleet agrees)."""
        with self._lock:
            return {k: dict(v) for k, v in self._audit_dissent.items()}

    def _hangs_locked(self):
        """Advance peer-hang state: a worker whose shard carries an
        abort-stage watchdog verdict is WEDGED (it could not step at
        all — a different failure class from a straggler, which is
        merely slow). A peer's verdict (host != this process's label)
        is held for the training loop, which raises it as a HangError
        so every worker aborts-and-restores together — the only
        recovery that works when a collective is missing a
        participant. Each (host, id) escalates exactly once."""
        local = distributed.host_label()
        for w in self._workers.values():
            h = w.hang
            if not isinstance(h, dict) or h.get("stage") != "abort":
                continue
            key = (w.host, h.get("id"))
            if w.host == local or key in self._hang_seen:
                continue
            self._hang_seen.add(key)
            if self._peer_hang is None:
                self._peer_hang = {"host": w.host, **h}

    def peer_hang(self) -> "dict | None":
        """The pending (unconsumed) peer-hang verdict, or None."""
        return self._peer_hang

    def take_peer_hang(self) -> "dict | None":
        """Consume the pending peer-hang verdict (one coordinated
        abort per hang episode)."""
        with self._lock:
            h = self._peer_hang
            self._peer_hang = None
            return h

    def poll(self) -> dict:
        """Re-scan the spool and return the fresh rollup."""
        now_epoch = time.time()
        with self._lock:
            self._scan()
            self._hangs_locked()
            self._scores = self._score_locked()
            self._stale = {
                w.host: round(now_epoch - w.ts, 3)
                for w in self._workers.values()
                if w.host is not None
                and now_epoch - w.ts > self.stale_after_s}
            fired = self._verdicts_locked()
            audit_fired = self._audit_vote_locked()
            self._export_locked(now_epoch)
            self._last_poll = time.monotonic()
        _agg_metrics()["polls"].inc()
        self._apply_policy(fired)
        self._apply_audit(audit_fired)
        return self.rollup()

    def poll_if_due(self):
        if self._poll_thread is not None:
            return  # the background thread owns the cadence
        if time.monotonic() - self._last_poll >= self.poll_interval_s:
            self.poll()

    # -- background polling ------------------------------------------------
    def start_polling(self):
        """Run poll() on a daemon thread (`singa-fleet-agg`) instead of
        the caller's cadence — for big fleets, where a synchronous spool
        rescan (every shard read + parsed) inside the training loop's
        `check_straggler_halt` would steal step time. The training hook
        then only reads the sticky halt verdict. Idempotent;
        `stop_polling` / `uninstall_aggregator` join the thread."""
        if self._poll_thread is not None and self._poll_thread.is_alive():
            return
        self._poll_stop.clear()

        def _loop():
            while not self._poll_stop.wait(
                    max(self.poll_interval_s, 0.05)):
                try:
                    self.poll()
                except Exception:
                    pass  # a bad shard scan must not kill the cadence

        self._poll_thread = threading.Thread(
            target=_loop, daemon=True, name="singa-fleet-agg")
        self._poll_thread.start()

    def stop_polling(self):
        self._poll_stop.set()
        t = self._poll_thread
        self._poll_thread = None
        if t is not None:
            t.join(timeout=5.0)

    # -- reading -----------------------------------------------------------
    def workers(self) -> list:
        with self._lock:
            return sorted((w for w in self._workers.values()
                           if w.host is not None),
                          key=lambda w: (w.host, w.pid))

    def straggler_scores(self) -> dict:
        with self._lock:
            return dict(self._scores)

    def halt_verdict(self) -> "dict | None":
        return self._halt

    def clear_halt(self):
        self._halt = None

    def rollup(self) -> dict:
        """The fleet-level view of the last poll: per-host rows plus the
        merged metric rollups."""
        now_epoch = time.time()
        with self._lock:
            rows = []
            for w in sorted(self._workers.values(),
                            key=lambda w: (w.host or "", w.pid or 0)):
                if w.host is None:
                    continue
                rows.append({
                    "host": w.host, "pid": w.pid, "seq": w.seq,
                    "age_s": round(max(0.0, now_epoch - w.ts), 3),
                    "stale": w.host in self._stale,
                    "steps": w.steps,
                    "step_rate": round(w.step_rate, 3),
                    "goodput_ratio":
                        round(float(w.goodput.get("goodput_ratio")), 4)
                        if isinstance(w.goodput, dict) else None,
                    "straggler_score":
                        round(self._scores.get(w.host, 0.0), 4),
                    "sustained": w.host in self._sustained,
                    "health": (w.health or {}).get("status")
                        if isinstance(w.health, dict) else None,
                    "hang": dict(w.hang)
                        if isinstance(w.hang, dict) else None,
                    "mem_bytes": int(w.mem.get("total_bytes") or 0)
                        if isinstance(w.mem, dict) else None,
                    "mem_regions": dict(w.mem.get("regions") or {})
                        if isinstance(w.mem, dict) else None,
                    # the per-replica serving columns (ROADMAP item 5):
                    # RPS, queue, occupancy, page util, TTFT, kv-cache
                    # bytes from the memory ledger, SLO attainment
                    "serve": {
                        "rps": w.serve.get("rps"),
                        "queue_depth": w.serve.get("queue_depth"),
                        "occupancy": w.serve.get("occupancy"),
                        "slots": w.serve.get("slots"),
                        "page_util": w.serve.get("page_util"),
                        "kv_cache_bytes": w.serve.get("kv_cache_bytes"),
                        "decode_tok_s": w.serve.get("decode_tok_s"),
                        "ttft_p50_s": w.serve.get("ttft_p50_s"),
                        "ttft_p99_s": w.serve.get("ttft_p99_s"),
                        "finished": w.serve.get("finished"),
                        "slo_attainment_pct":
                            slo.serve_attainment_pct(w.serve),
                        "slo_breaching":
                            ((w.serve.get("slo") or {})
                             .get("breaching") or []),
                        # graceful-drain visibility (ROADMAP item 5):
                        # the router shows a replica as draining the
                        # moment its engine stops admitting
                        "draining": bool(w.serve.get("draining")),
                    } if isinstance(w.serve, dict) else None,
                    # the replica's own headroom row (fleet_capacity
                    # shard line): binding wall + headroom for the
                    # /fleetz column, last shadow decision when the
                    # worker runs a scaler
                    "capacity": dict(w.capacity)
                    if isinstance(w.capacity, dict) else None,
                    # param-integrity audit (fleet_audit shard line):
                    # the fingerprint itself plus this poll's vote
                    # outcome for the /fleetz integrity column
                    "audit": {
                        "fingerprint": list(
                            w.audit.get("fingerprint") or []),
                        "count": w.audit.get("count"),
                        "dissent": dict(
                            self._audit_dissent.get(w.host) or {})
                        or None,
                    } if isinstance(w.audit, dict) else None,
                    # regression observatory (fleet_regress shard
                    # line): active-episode count + last verdict for
                    # the /fleetz regression column and the
                    # localization vote
                    "regress": dict(w.regress)
                    if isinstance(w.regress, dict) else None,
                })
            # worst-HBM host: max live bytes across workers that
            # published a memory snapshot (freshest shard per host
            # already won above)
            with_mem = [r for r in rows if r["mem_bytes"] is not None]
            worst = max(with_mem, key=lambda r: r["mem_bytes"]) \
                if with_mem else None
            merged = merge_metric_snapshots(
                {w.host: w.metrics for w in self._workers.values()
                 if w.host is not None})
            return {
                "fleet_dir": self.fleet_dir,
                "n_workers": len(rows),
                "n_stale": len(self._stale),
                "threshold": self.threshold,
                "sustain": self.sustain,
                "policy": self._resolved_policy(),
                "workers": rows,
                "stragglers": sorted(self._sustained),
                "wedged": sorted(r["host"] for r in rows
                                 if r["hang"] is not None
                                 and r["hang"].get("stage") == "abort"),
                "halt": self._halt,
                "peer_hang": self._peer_hang,
                "audit_dissent": {k: dict(v) for k, v
                                  in self._audit_dissent.items()},
                "worst_mem_host": worst["host"] if worst else None,
                "worst_mem_bytes": worst["mem_bytes"] if worst else None,
                "metrics": merged,
            }

    # -- merged trace ------------------------------------------------------
    def trace_events(self) -> dict:
        """The merged Chrome Trace Event Format object: one process
        (track) per worker, span + collective slices on it, clocks
        aligned onto the shared wall timeline via each worker's
        (epoch, perf_counter) handshake."""
        events = []
        with self._lock:
            workers = [w for w in self._workers.values()
                       if w.host is not None]
            workers.sort(key=lambda w: (w.host, w.pid))
            for i, w in enumerate(workers):
                events.append({"ph": "M", "name": "process_name",
                               "pid": w.pid, "tid": 0,
                               "args": {"name": f"{w.host} "
                                                f"(pid {w.pid})"}})
                events.append({"ph": "M", "name": "process_sort_index",
                               "pid": w.pid, "tid": 0,
                               "args": {"sort_index": i}})
                off = w.clock_offset
                startup_tids = set()
                for rec in w.spans.values():
                    t0 = rec.get("t0")
                    dur = rec.get("dur")
                    if t0 is None or dur is None:
                        continue
                    if (rec.get("span_kind") or "span") == "startup":
                        # the replica cold-start observatory's phase
                        # slices ride the span ring on a synthetic tid
                        # — name the track once below
                        startup_tids.add(int(rec.get("tid") or 0))
                    events.append({
                        "name": (rec.get("name") or "?"
                                 ).rsplit("/", 1)[-1],
                        "cat": rec.get("span_kind") or "span",
                        "ph": "X",
                        "ts": round((float(t0) + off) * 1e6, 3),
                        "dur": round(float(dur) * 1e6, 3),
                        "pid": w.pid,
                        "tid": int(rec.get("tid") or 0),
                        "args": {"path": rec.get("name"),
                                 "host": w.host},
                    })
                for tid in sorted(startup_tids):
                    events.append({"ph": "M", "name": "thread_name",
                                   "pid": w.pid, "tid": tid,
                                   "args": {"name": "startup"}})
                if isinstance(w.serve, dict):
                    # the request-level serving view: per-request
                    # queued/prefill/decode spans + decode-step slices
                    # + the flow events linking them, aligned onto the
                    # shared wall clock via the SAME handshake offset —
                    # a multi-replica trace shows requests flowing
                    # through workers. When the worker's span ring
                    # already published serving.engine_step slices
                    # (span records on, the normal case), the sync ring
                    # must not overlay near-identical duplicates on the
                    # same tid — the flows bind inside the real ones.
                    have_step_spans = any(
                        (rec.get("name") or "").rsplit("/", 1)[-1]
                        == "serving.engine_step"
                        for rec in w.spans.values())
                    # finished timelines PLUS the in-flight ones the
                    # shard carried at publish: a replica SIGKILLed
                    # mid-request leaves its partial work (the victim
                    # track of a failover trace) in `active`
                    timelines = list(w.serve.get("timelines") or [])
                    timelines.extend(w.serve.get("active") or [])
                    syncs = w.serve.get("syncs") or []
                    events.extend(slo._track_metadata(
                        timelines, syncs, w.pid))
                    events.extend(slo.request_trace_events(
                        timelines, syncs, w.pid, offset=off,
                        emit_sync_slices=not have_step_spans))
        # the router's own track (queue + dispatch hops + the
        # cross-process trace_ctx flow ends), when this process IS the
        # routing coordinator — replicas join the flow by trace id
        try:
            from . import router as router_mod
            if router_mod.get_router() is not None:
                events.extend(router_mod.router_trace_events())
        except Exception:
            pass
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def export_trace(self, path: str) -> str:
        """Write the merged trace JSON to `path` (open it in Perfetto /
        chrome://tracing) and return the path."""
        trace = self.trace_events()
        with open(path, "w", encoding="utf-8") as f:
            json.dump(trace, f, separators=(",", ":"))
        return path


# ---- module singletons -----------------------------------------------------

_writers: "list[ShardWriter]" = []
_owned_dirs: "list[str]" = []
_shard_writer: "ShardWriter | None" = None
_aggregator: "FleetAggregator | None" = None
_lock = threading.Lock()


def start_shard_writer(fleet_dir: "str | None" = None,
                       **kwargs) -> ShardWriter:
    """Start (or return) the process shard writer. A second call with a
    DIFFERENT fleet_dir replaces the old writer (closed first)."""
    global _shard_writer
    with _lock:
        w = _shard_writer
        if w is not None:
            if fleet_dir is None \
                    or os.path.abspath(fleet_dir) == w.fleet_dir:
                return w
            w.close()
        _shard_writer = ShardWriter(fleet_dir, **kwargs)
        return _shard_writer


def stop_shard_writer():
    """Close the process shard writer (idempotent)."""
    global _shard_writer
    with _lock:
        if _shard_writer is not None:
            _shard_writer.close()
            _shard_writer = None


def get_shard_writer() -> "ShardWriter | None":
    return _shard_writer


def install_aggregator(fleet_dir: "str | None" = None,
                       **kwargs) -> FleetAggregator:
    """Install (or return) the process FleetAggregator — the object
    /fleetz, check_straggler_halt and export_trace answer from. May be
    passed a ready FleetAggregator via `fleet_dir=None, aggregator=`."""
    global _aggregator
    agg = kwargs.pop("aggregator", None)
    with _lock:
        if agg is not None:
            if _aggregator is not None and _aggregator is not agg:
                _aggregator.stop_polling()  # don't leak the old cadence
            _aggregator = agg
            return agg
        if _aggregator is not None:
            return _aggregator
        if fleet_dir is None:
            raise ValueError("install_aggregator needs a fleet_dir "
                             "(or aggregator=)")
        _aggregator = FleetAggregator(fleet_dir, **kwargs)
        return _aggregator


def uninstall_aggregator():
    global _aggregator
    with _lock:
        agg = _aggregator
        _aggregator = None
    if agg is not None:
        agg.stop_polling()


def get_aggregator() -> "FleetAggregator | None":
    return _aggregator


def uninstall():
    """Full fleet teardown (the conftest contract): every shard writer
    closed (threads joined), the aggregator dropped, the span-record
    ring disabled, and spool temp dirs this module created removed."""
    stop_shard_writer()
    for w in list(_writers):
        w.close(final_publish=False)
    uninstall_aggregator()
    observe.disable_span_records()
    for d in list(_owned_dirs):
        shutil.rmtree(d, ignore_errors=True)
        _owned_dirs.remove(d)


def export_trace(path: str) -> str:
    """Poll the installed aggregator and write the merged trace JSON."""
    agg = _aggregator
    if agg is None:
        raise RuntimeError("no FleetAggregator installed "
                           "(fleet.install_aggregator(fleet_dir))")
    agg.poll()
    return agg.export_trace(path)


def check_straggler_halt(step: "int | None" = None):
    """Training-loop hook (resilience.TrainController calls it every
    step): no-op without an aggregator; otherwise polls on the
    aggregator's cadence and raises FleetStragglerError once a sustained
    straggler verdict landed under the halt policy — or, when a PEER
    published an abort-stage watchdog hang verdict, raises
    `watchdog.HangError` so this worker aborts-and-restores in lockstep
    with the wedged one (the coordinated recovery a missing-participant
    collective requires; consumed once per hang episode). Raising from
    the LOOP (not the aggregator's caller) is the point — the
    controller's HealthError path saves a final checkpoint and attaches
    the report, and its HangError path restores-and-restarts."""
    agg = _aggregator
    if agg is None:
        return
    agg.poll_if_due()
    h = agg.halt_verdict()
    if h is not None:
        raise FleetStragglerError(
            f"sustained straggler {h['host']} "
            f"(score {h['score']:.2f} > {agg.threshold:.2f} for "
            f"{agg.sustain} polls); elastic restart should exclude it"
            + (f" [step {step}]" if step is not None else ""),
            hosts=(h["host"],), score=h["score"])
    ph = agg.take_peer_hang()
    if ph is not None:
        from . import watchdog
        observe.get_registry().emit(
            {"kind": "fleet", "event": "peer_hang",
             "host": ph.get("host"), "op": ph.get("op"),
             "seconds": ph.get("seconds"), "step": step})
        raise watchdog.HangError(
            f"peer {ph.get('host')} wedged in {ph.get('op')!r} "
            f"({ph.get('seconds')}s past its deadline): coordinated "
            "abort-and-restore"
            + (f" [step {step}]" if step is not None else ""),
            op=ph.get("op"), seconds=ph.get("seconds"),
            hosts=(ph.get("host"),))


def fleet_report() -> str:
    """Text block for /fleetz: one row per worker plus fleet rollups."""
    agg = _aggregator
    if agg is None:
        return ("no FleetAggregator installed "
                "(singa_tpu.fleet.install_aggregator(fleet_dir))")
    roll = agg.poll()
    local = distributed.host_label()
    lines = [
        f"== fleet ==  coordinator pid {os.getpid()}  "
        f"spool {roll['fleet_dir']}",
        f"workers: {roll['n_workers']} ({roll['n_stale']} stale)   "
        f"policy: {roll['policy']}   "
        f"straggler threshold: {roll['threshold']:.2f} "
        f"(sustain {roll['sustain']} polls)",
        f"{'host':<12} {'pid':>7} {'seq':>5} {'age_s':>7} {'steps':>7} "
        f"{'step/s':>8} {'goodput':>8} {'mem_mb':>8} {'straggler':>10} "
        f"state",
    ]
    for r in roll["workers"]:
        # wedged outranks everything: a worker with an abort-stage hang
        # verdict could not step AT ALL (vs. a straggler, merely slow)
        state = "WEDGED" if (r.get("hang") or {}).get("stage") \
            == "abort" else (
            "STALE" if r["stale"] else (
                "STRAGGLER" if r["sustained"] else (r["health"] or "ok")))
        mark = "*" if r["host"] == local else " "
        gp = f"{r['goodput_ratio']:.2f}" \
            if r["goodput_ratio"] is not None else "-"
        mem = f"{r['mem_bytes'] / 1e6:.1f}" \
            if r.get("mem_bytes") is not None else "-"
        lines.append(
            f"{r['host']:<11}{mark} {r['pid']:>7} {r['seq']:>5} "
            f"{r['age_s']:>7.2f} {r['steps']:>7} "
            f"{r['step_rate']:>8.2f} {gp:>8} {mem:>8} "
            f"{r['straggler_score']:>10.3f} {state}")
    serving = [r for r in roll["workers"] if r.get("serve")]
    if serving:
        lines.append("== fleet serving ==")
        lines.append(
            f"{'host':<12} {'rps':>7} {'queue':>6} {'occ':>7} "
            f"{'pages':>7} {'ttft_p50_ms':>12} {'ttft_p99_ms':>12} "
            f"{'kv_mb':>8} {'slo_pct':>8} {'headroom':>9} breaching")
        for r in serving:
            s = r["serve"]
            cap = r.get("capacity") or {}
            head = f"{100.0 * cap['headroom_frac']:.0f}%" \
                   f"({cap.get('wall') or '-'})" \
                if cap.get("headroom_frac") is not None else "-"
            occ = f"{s['occupancy']}/{s['slots']}" \
                if s.get("slots") is not None else "-"
            pu = f"{100.0 * s['page_util']:.0f}%" \
                if s.get("page_util") is not None else "-"
            p50 = f"{s['ttft_p50_s'] * 1e3:.1f}" \
                if s.get("ttft_p50_s") is not None else "-"
            p99 = f"{s['ttft_p99_s'] * 1e3:.1f}" \
                if s.get("ttft_p99_s") is not None else "-"
            kv = f"{s['kv_cache_bytes'] / 1e6:.2f}" \
                if s.get("kv_cache_bytes") is not None else "-"
            att = f"{s['slo_attainment_pct']:.1f}" \
                if s.get("slo_attainment_pct") is not None else "-"
            lines.append(
                f"{r['host']:<12} {s.get('rps') or 0.0:>7.2f} "
                f"{s.get('queue_depth') or 0:>6} {occ:>7} {pu:>7} "
                f"{p50:>12} {p99:>12} {kv:>8} {att:>8} {head:>9} "
                f"{','.join(s.get('slo_breaching') or []) or 'none'}"
                + (" [draining]" if s.get("draining") else ""))
    audited = [r for r in roll["workers"] if r.get("audit")]
    if audited:
        # the correctness columns: each replica's fingerprint (folded
        # to one word for the table; /auditz has the per-group view)
        # and the vote outcome — a dissenter names its first diverging
        # layer group right here
        lines.append("== fleet integrity ==")
        lines.append(f"{'host':<12} {'fingerprint':>12} {'checks':>7} "
                     f"vote")
        for r in audited:
            a = r["audit"]
            folded = 0
            for _, v in (a.get("fingerprint") or []):
                folded = (folded * 16777619) ^ int(v)
                folded &= 0xFFFFFFFF
            d = a.get("dissent")
            vote = (f"DISSENT (first diverging group: "
                    f"{d.get('first_group')}, "
                    f"{d.get('majority')}/{d.get('voters')} against)"
                    if d else "agree")
            lines.append(f"{r['host']:<12} {folded:>#12x} "
                         f"{a.get('count') or 0:>7} {vote}")
    # the serving control plane, when one is installed in this process
    # (the router coordinator is usually also the fleet coordinator)
    try:
        from . import router as _router_mod
        lines.extend(_router_mod.fleetz_lines())
    except Exception:
        pass
    # the correctness observatory's canary/replay verdict columns,
    # when one is installed in this process
    try:
        from . import audit as _audit_mod
        lines.extend(_audit_mod.fleetz_lines())
    except Exception:
        pass
    # the regression observatory's per-host column + localization vote
    # over the fleet_regress shard lines
    try:
        from . import regress as _regress_mod
        lines.extend(_regress_mod.fleetz_lines())
    except Exception:
        pass
    steps_total = 0
    for s in (roll["metrics"].get("singa_steps_total") or
              {}).get("series", {}).values():
        steps_total += int(s.get("value", 0.0))
    worst = roll.get("worst_mem_host")
    lines.append(f"fleet steps: {steps_total}   "
                 f"sustained stragglers: "
                 f"{','.join(roll['stragglers']) or 'none'}   "
                 f"wedged: {','.join(roll['wedged']) or 'none'}   "
                 f"halt: {roll['halt'] or 'none'}   "
                 f"worst-HBM host: "
                 + (f"{worst} ({roll['worst_mem_bytes'] / 1e6:.1f} MB)"
                    if worst else "none (no memory shards)"))
    return "\n".join(lines)


# ---- CLI: the multi-process straggler A/B ----------------------------------
# `--worker` runs one telemetry-publishing training leg (a tiny real
# model, or --synthetic for a model-free span/collective loop); `--ab`
# spawns N workers, injects a FaultPlan delay into ONE worker's
# collectives (`fault_point("comm.collective")`), and asserts from the
# COORDINATOR side — via /fleetz and the exported merged trace — that
# the slow host is detected within K steps and visibly slow on its
# trace track. Writes FLEET_r*.json.

def _worker_main(args) -> int:
    if args.host:
        os.environ["SINGA_FLEET_HOST"] = args.host
    if args.delay_collectives > 0:
        from . import resilience
        plan = resilience.FaultPlan()
        plan.delay("comm.collective", args.delay_collectives,
                   times=10 ** 9)
        resilience.install_fault_plan(plan)
    writer = start_shard_writer(args.fleet_dir,
                                interval_s=args.publish_interval)
    from .parallel.communicator import Communicator
    import jax.numpy as jnp
    comm = Communicator()  # world 1: the eager per-step host collective
    tick = jnp.ones(())
    model = tx = ty = None
    if not args.synthetic:
        from .resilience import _worker_build
        model, tx, ty = _worker_build(args.mesh_devices, args.batch,
                                      args.seed)
    for _ in range(args.steps):
        t0 = time.perf_counter()
        if args.synthetic:
            with observe.span(STEP_SPAN_LEAF):
                if args.step_sleep:
                    time.sleep(args.step_sleep)
                comm.all_reduce(tick)
            observe.record_step(time.perf_counter() - t0)
        else:
            model(tx, ty)  # spans model.step + records the step itself
            comm.all_reduce(tick)
            if args.step_sleep:
                time.sleep(args.step_sleep)
        writer.publish()
    stop_shard_writer()
    print(json.dumps({"host": distributed.host_label(),
                      "steps": args.steps,
                      "mode": "synthetic" if args.synthetic else "model"}))
    return 0


def _spawn_fleet_worker(py, root, args, idx, delay):
    import subprocess
    import sys
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               SINGA_FLEET_HOST=f"host{idx}")
    env.pop("SINGA_TPU_DIAG_PORT", None)
    if not args.synthetic:
        env["XLA_FLAGS"] = (f"--xla_force_host_platform_device_count="
                            f"{args.mesh_devices}")
    cmd = [py, "-m", "singa_tpu.fleet", "--worker",
           "--fleet-dir", args.fleet_dir,
           "--steps", str(args.steps),
           "--step-sleep", str(args.step_sleep),
           "--publish-interval", str(args.publish_interval),
           "--mesh-devices", str(args.mesh_devices),
           "--batch", str(args.batch), "--seed", str(args.seed),
           "--delay-collectives", str(delay)]
    if args.synthetic:
        cmd.append("--synthetic")
    return subprocess.Popen(cmd, cwd=root, env=env,
                            stdout=sys.stderr, stderr=sys.stderr)


def _http_get(url: str) -> bytes:
    from urllib.request import urlopen
    with urlopen(url, timeout=30) as r:
        return r.read()


def _ab_main(args) -> int:
    import sys
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    work = tempfile.mkdtemp(prefix="singa_fleet_ab_")
    args.fleet_dir = os.path.join(work, "spool")
    os.makedirs(args.fleet_dir, exist_ok=True)
    slow_idx = args.workers - 1
    slow_host = f"host{slow_idx}"
    rec = {"workers": args.workers, "steps": args.steps,
           "delay_s": args.delay, "threshold": args.threshold,
           "detect_steps": args.detect_steps, "slow_host": slow_host,
           "mode": "synthetic" if args.synthetic else "model",
           "ok": False}
    agg = install_aggregator(args.fleet_dir, threshold=args.threshold,
                             stale_after_s=30.0,
                             poll_interval_s=0.05)
    from . import diag
    srv = diag.start_diag_server(port=0)
    procs = [_spawn_fleet_worker(sys.executable, root, args, i,
                                 args.delay if i == slow_idx else 0.0)
             for i in range(args.workers)]
    detected = False
    detect_steps = None
    detect_scores = None
    deadline = time.monotonic() + args.timeout
    try:
        while time.monotonic() < deadline:
            agg.poll()
            scores = agg.straggler_scores()
            if len(scores) == args.workers and not detected:
                slow = scores.get(slow_host, 0.0)
                others = [v for h, v in scores.items() if h != slow_host]
                if slow > args.threshold \
                        and all(v <= args.threshold for v in others):
                    detected = True
                    detect_scores = {h: round(v, 3)
                                     for h, v in scores.items()}
                    detect_steps = max(
                        (w.steps for w in agg.workers()
                         if w.host == slow_host), default=None)
            if all(p.poll() is not None for p in procs):
                break
            time.sleep(0.05)
        for p in procs:
            if p.poll() is None:
                p.kill()
            p.wait()
        rec["worker_rcs"] = [p.returncode for p in procs]
        agg.poll()
        # the acceptance surface is the COORDINATOR's HTTP endpoints
        fleetz = _http_get(srv.url + "/fleetz").decode("utf-8")
        rec["fleetz_lists_all_hosts"] = all(
            f"host{i}" in fleetz for i in range(args.workers))
        rec["detected"] = detected
        rec["steps_at_detection"] = detect_steps
        rec["scores_at_detection"] = detect_scores
        rec["final_scores"] = {h: round(v, 3) for h, v
                               in agg.straggler_scores().items()}
        trace_bytes = _http_get(srv.url + "/fleetz/trace")
        trace = json.loads(trace_bytes)
        events = trace.get("traceEvents", [])
        tracks = {e["pid"] for e in events
                  if e.get("ph") == "M"
                  and e.get("name") == "process_name"}
        slow_pids = {e["pid"] for e in events
                     if e.get("ph") == "M"
                     and e.get("name") == "process_name"
                     and slow_host in str(e.get("args", {}).get("name"))}
        gap_us = max((e.get("dur", 0.0) for e in events
                      if e.get("ph") == "X" and e.get("cat") == "comm"
                      and e.get("pid") in slow_pids), default=0.0)
        schema_ok = (isinstance(events, list) and events
                     and all(isinstance(e.get("name"), str)
                             and "ph" in e and "pid" in e
                             for e in events)
                     and all("ts" in e and "dur" in e and "tid" in e
                             for e in events if e.get("ph") == "X"))
        rec["trace_schema_ok"] = bool(schema_ok)
        rec["trace_tracks"] = len(tracks)
        rec["trace_events"] = len(events)
        rec["slow_gap_ms"] = round(gap_us / 1000.0, 3)
        out_trace = os.path.abspath(args.trace_out) \
            if args.trace_out else None
        if out_trace:
            with open(out_trace, "wb") as f:
                f.write(trace_bytes)  # the body already fetched above
            rec["trace_path"] = out_trace
        rec["ok"] = bool(
            all(rc == 0 for rc in rec["worker_rcs"])
            and detected
            and (detect_steps is not None
                 and detect_steps <= args.detect_steps)
            and rec["fleetz_lists_all_hosts"]
            and schema_ok
            and len(tracks) == args.workers
            and gap_us >= args.delay * 1e6 * 0.8)
    finally:
        diag.stop_diag_server()
        uninstall()
        shutil.rmtree(work, ignore_errors=True)
    out = os.path.abspath(args.out)
    with open(out, "w", encoding="utf-8") as f:
        json.dump(rec, f, indent=1)
        f.write("\n")
    print(json.dumps(rec, indent=1))
    return 0 if rec["ok"] else 1


def main(argv=None) -> int:
    import argparse
    p = argparse.ArgumentParser(
        prog="python -m singa_tpu.fleet",
        description="fleet observability harness (worker + straggler A/B)")
    p.add_argument("--worker", action="store_true",
                   help="run one shard-publishing training leg")
    p.add_argument("--ab", action="store_true",
                   help="run the multi-process straggler A/B")
    p.add_argument("--fleet-dir", default=None)
    p.add_argument("--workers", type=int, default=3)
    p.add_argument("--steps", type=int, default=12)
    p.add_argument("--step-sleep", type=float, default=0.03)
    p.add_argument("--publish-interval", type=float, default=0.1)
    p.add_argument("--mesh-devices", type=int, default=2)
    p.add_argument("--batch", type=int, default=16)
    p.add_argument("--seed", type=int, default=7)
    p.add_argument("--host", default=None)
    p.add_argument("--synthetic", action="store_true",
                   help="no model: span + eager-collective loop only")
    p.add_argument("--delay-collectives", type=float, default=0.0,
                   help="FaultPlan delay injected at comm.collective")
    p.add_argument("--delay", type=float, default=0.05,
                   help="A/B: collective delay on the slow worker")
    p.add_argument("--threshold", type=float, default=0.5)
    p.add_argument("--detect-steps", type=int, default=5)
    p.add_argument("--timeout", type=float, default=600.0)
    p.add_argument("--trace-out", default=None)
    p.add_argument("--out", default="FLEET_r01.json")
    args = p.parse_args(argv)
    if args.worker:
        if not args.fleet_dir:
            p.error("--worker requires --fleet-dir")
        return _worker_main(args)
    if args.ab:
        return _ab_main(args)
    p.error("pass --worker or --ab")
    return 2


__all__ = [
    "ShardWriter", "FleetAggregator", "FleetStragglerError",
    "read_shard", "merge_metric_snapshots",
    "start_shard_writer", "stop_shard_writer", "get_shard_writer",
    "install_aggregator", "uninstall_aggregator", "get_aggregator",
    "uninstall", "export_trace", "check_straggler_halt", "fleet_report",
    "SHARD_VERSION", "SHARD_SUFFIX", "STEP_SPAN_LEAF",
]

if __name__ == "__main__":
    import sys
    # run under the CANONICAL module, not this __main__ alias: the CLI
    # installs module singletons (the aggregator, the shard writer) that
    # the diag server's handlers reach via `import singa_tpu.fleet` —
    # under runpy those are two different module objects otherwise
    from singa_tpu.fleet import main as _main
    sys.exit(_main())
