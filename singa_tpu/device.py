"""Device abstraction over jax.Device.

Reference parity: SINGA's C++ `Device` (include/singa/core/device.h:57) owns
op submission (`Exec` -> immediate or graph), memory blocks, sync, graph
replay, and profiling verbosity; `Platform` (device.h:311) discovers GPUs and
Python wraps it thinly (python/singa/device.py:29-135).

TPU-native redesign: XLA owns memory and the compiled graph, so `Device` here
is a *policy object*: which jax.Device tensors land on, whether Model-level
graph (jit) buffering is on, profiling verbosity, and the per-device PRNG
stream (the reference keeps curand state in `Context`, common.h:99-128).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

# process-global: jax.profiler allows one active trace per process
_active_trace_dir: "str | None" = None


class Device:
    """A compute device. Holds placement + graph/profiling policy + RNG."""

    def __init__(self, jax_device: "jax.Device", id: int = 0, lang: str = "kTpu"):
        self.jax_device = jax_device
        self.id = id
        self.lang = lang
        # Graph buffering flag: mirrors Device::graph_enabled_ toggled by
        # EnableGraph (device.h:142). When True, Model.train_one_batch traces
        # into a jitted executable instead of running eagerly.
        self.graph_enabled = False
        # Profiling verbosity 0-3 + warmup skip, mirrors device.h:115-129.
        self.verbosity = 0
        self.skip_iteration = 5
        # Filled by Model when verbosity > 0 (replaces the reference's
        # per-node cudaEvent timing, scheduler.cc:240-295).
        self.step_times = []       # seconds per profiled step
        # XLA cost analysis of the compiled step: populated at AOT build
        # time by singa_tpu.introspect (model.py routes every step build
        # through explicit lower/compile stages), so the verbosity>=2
        # GFLOP/TFLOP-s lines below print real numbers with no extra
        # re-lowering pass.
        self.cost_analysis = None
        # Per-device PRNG stream (reference: curandGenerator in Context).
        self._rng_key = jax.random.key(0, impl="threefry2x32")
        self._rng_key = jax.device_put(self._rng_key, jax_device)

    # ---- RNG ------------------------------------------------------------
    def SetRandSeed(self, seed: int):
        self._rng_key = jax.device_put(
            jax.random.key(int(seed), impl="threefry2x32"), self.jax_device)

    def rand_key(self):
        """Split off a fresh PRNG key (functional curandGenerate analog)."""
        self._rng_key, sub = jax.random.split(self._rng_key)
        return sub

    @property
    def rng_state(self):
        return self._rng_key

    @rng_state.setter
    def rng_state(self, key):
        # Normalize RAW uint32 keys (legacy jax.random.PRNGKey) to TYPED
        # keys: the framework threads rng_state through jitted/shard_mapped
        # steps, and a mid-stream dtype flip (typed <-> raw) fragments the
        # executable cache into variants with different buffer layouts —
        # an INVALID_ARGUMENT buffer-count crash at dispatch time.
        try:
            if (isinstance(key, jax.Array)
                    and not jnp.issubdtype(key.dtype, jax.dtypes.prng_key)
                    and key.ndim == 1 and key.shape[0] == 2
                    and key.dtype == jnp.uint32):
                key = jax.random.wrap_key_data(key)
        except TypeError:
            # tracers/abstract values: shape/dtype probing above can raise
            # on them; they pass through untouched. Anything else (e.g. a
            # malformed key array) propagates — silently threading a bad
            # key would fragment the executable cache, the exact failure
            # this normalization exists to prevent.
            pass
        self._rng_key = key

    # ---- graph control (parity with core_device.i) ----------------------
    def EnableGraph(self, enable: bool = True):
        self.graph_enabled = enable

    def ResetGraph(self):
        # XLA owns the executable cache; Model drops its compiled step.
        pass

    def Sync(self):
        """Fence: wait for all queued device work (Device::Sync)."""
        try:
            self.jax_device.client.synchronize_all_activity()  # type: ignore[attr-defined]
        except Exception:
            # Portable fallback: a tiny transfer forces a sync point.
            jax.device_put(np.zeros(()), self.jax_device).block_until_ready()

    # ---- profiling (device.h:115-129) -----------------------------------
    def SetVerbosity(self, v: int):
        self.verbosity = int(v)

    def SetSkipIteration(self, n: int):
        self.skip_iteration = int(n)

    def PrintTimeProfiling(self):
        """Per-step timing summary (reference Graph::PrintTimeProfiling,
        scheduler.cc:240-295; fwd/bwd split is replaced by whole-step wall
        time + XLA cost analysis since XLA fuses across the phases)."""
        if not self.step_times:
            print("time profiling: no steps recorded "
                  "(SetVerbosity(>=1) before training)")
            return
        t = np.asarray(self.step_times)
        print(f"time profiling: {len(t)} steps, "
              f"mean {t.mean() * 1e3:.3f} ms, std {t.std() * 1e3:.3f} ms, "
              f"min {t.min() * 1e3:.3f} ms")
        if self.verbosity >= 2 and self.cost_analysis:
            ca = self.cost_analysis
            flops = ca.get("flops", 0.0)
            bytes_ = ca.get("bytes accessed", 0.0)
            achieved = flops / max(t.mean(), 1e-12) / 1e12
            print(f"  XLA cost: {flops / 1e9:.2f} GFLOP/step, "
                  f"{bytes_ / 1e6:.1f} MB accessed/step, "
                  f"{achieved:.2f} TFLOP/s achieved")
            try:
                from .introspect import peak_tflops
                peak = peak_tflops(
                    getattr(self.jax_device, "device_kind", ""))
            except Exception:
                peak = None
            if peak:
                print(f"  MFU: {achieved / peak * 100.0:.2f}% of "
                      f"{peak:g} TFLOP/s peak")
        if self.verbosity >= 3 and self.cost_analysis:
            for k, v in sorted(self.cost_analysis.items()):
                if isinstance(v, (int, float)):
                    print(f"  {k}: {v:.3g}")

    # ---- trace capture ---------------------------------------------------
    # The reference's deepest profiling level is per-op CUDA-event tables
    # (scheduler.cc:276-295). The TPU analog is an xplane trace: per-HLO
    # timelines viewable in TensorBoard/xprof/Perfetto. jax.profiler is
    # process-global, so the active-trace flag lives at module level —
    # Start/Stop pair up correctly across different Device objects.
    def StartTrace(self, log_dir: str):
        """Begin capturing a jax profiler trace into `log_dir`."""
        global _active_trace_dir
        if _active_trace_dir is not None:
            raise RuntimeError(
                f"a trace into {_active_trace_dir} is already active; "
                "StopTrace() it first (the profiler is process-global)")
        jax.profiler.start_trace(log_dir)
        _active_trace_dir = log_dir

    def StopTrace(self) -> "str | None":
        """Stop the capture; returns the log dir. Idempotent: with no
        trace active (never started, or already stopped — including by a
        second StopTrace or by jax.profiler directly) it returns None
        cleanly instead of raising, so shutdown paths can call it
        unconditionally."""
        global _active_trace_dir
        out = _active_trace_dir
        if out is not None:
            try:
                jax.profiler.stop_trace()
            except Exception:
                # someone stopped the process-global profiler under us;
                # idempotence beats raising — the flag reset below keeps
                # future StartTrace working either way
                pass
            finally:
                _active_trace_dir = None  # never wedge future StartTrace
        return out

    # ---- info ------------------------------------------------------------
    @property
    def platform(self) -> str:
        return self.jax_device.platform

    def is_host(self) -> bool:
        return self.jax_device.platform == "cpu"

    def __repr__(self):
        return f"Device(lang={self.lang}, id={self.id}, jax={self.jax_device})"


class _Platform:
    """Device discovery, mirrors `Platform` (device.h:311-386)."""

    def __init__(self):
        self._cache = {}

    def _accel_devices(self):
        devs = [d for d in jax.devices() if d.platform != "cpu"]
        return devs if devs else jax.devices()

    def GetNumGPUs(self) -> int:  # name kept for parity; counts accelerators
        return len(self._accel_devices())

    def num_tpus(self) -> int:
        return self.GetNumGPUs()

    def device(self, kind: str, idx: int) -> Device:
        key = (kind, idx)
        if key not in self._cache:
            if kind == "host":
                jd = jax.local_devices(backend="cpu")[idx]
                self._cache[key] = Device(jd, id=idx, lang="kCpp")
            else:
                jd = self._accel_devices()[idx]
                self._cache[key] = Device(jd, id=idx, lang="kTpu")
        return self._cache[key]


platform = _Platform()

# ---- module-level API (parity with python/singa/device.py) ---------------

_default_device: Device | None = None


def get_default_device() -> Device:
    """Host CPU device (reference returns the singleton CppCPU)."""
    global _default_device
    if _default_device is None:
        _default_device = platform.device("host", 0)
    return _default_device


def create_tpu_device(set_default: bool = False) -> Device:
    """First attached TPU chip (reference: create_cuda_gpu)."""
    d = platform.device("accel", 0)
    if set_default:
        global _default_device
        _default_device = d
    return d


def create_tpu_device_on(device_id: int) -> Device:
    """TPU chip by index (reference: create_cuda_gpu_on, device.py:103)."""
    return platform.device("accel", device_id)


# Aliases so code written against the reference API keeps working.
create_cuda_gpu = create_tpu_device
create_cuda_gpu_on = create_tpu_device_on


def create_cpu_device() -> Device:
    return get_default_device()


def best_device() -> Device:
    """The fastest attached device: TPU if present, else host CPU."""
    accel = [d for d in jax.devices() if d.platform != "cpu"]
    return platform.device("accel", 0) if accel else get_default_device()


def enable_lazy_alloc(flag: bool):
    """No-op: XLA allocates lazily by construction (ref device.py:133)."""
    del flag


# ---- reference-name query parity (python/singa/device.py:29-99) ---------
# "GPU" queries answer for the attached accelerators (TPU chips here);
# OpenCL was never compiled into the reference's Python wheels either, so
# those queries mirror its disabled-build behavior.

def get_num_gpus() -> int:
    return platform.GetNumGPUs()


def get_gpu_ids():
    return list(range(platform.GetNumGPUs()))


def get_gpu_mem_size(id: int):  # noqa: A002  (name mandated by parity)
    dev = platform.device("accel", id)
    stats = getattr(dev.jax_device, "memory_stats", lambda: None)()
    if stats:
        return (stats.get("bytes_limit", 0), stats.get("bytes_in_use", 0))
    return (0, 0)


def device_query(id: int, verbose=False):  # noqa: A002
    dev = platform.device("accel", id)
    info = {"id": id, "kind": getattr(dev.jax_device, "device_kind", "?"),
            "platform": dev.platform}
    if verbose:
        print(info)
    return info


def create_cuda_gpus(num: int):
    """A list of the first `num` accelerator Devices."""
    return [platform.device("accel", i) for i in range(num)]


def create_cuda_gpus_on(device_ids):
    return [platform.device("accel", i) for i in device_ids]


def get_num_opencl_platforms():
    raise AssertionError(
        "built without OpenCL (parity with the reference's USE_OPENCL=OFF "
        "wheels); use the TPU/CPU devices")


def get_num_opencl_devices():
    raise AssertionError(
        "built without OpenCL (parity with the reference's USE_OPENCL=OFF "
        "wheels); use the TPU/CPU devices")


def create_opencl_device():
    raise AssertionError(
        "built without OpenCL (parity with the reference's USE_OPENCL=OFF "
        "wheels); use the TPU/CPU devices")


create_tpu_devices = create_cuda_gpus
