"""Performance regression observatory: persistent latency baselines,
online change-point detection, cause attribution, and auto-captured
evidence bundles.

The stack's perf-regression story was offline only: tools/bench_trend.py
gates checked-in bench rounds, but nothing watched the LIVE fenced
latencies the runtime already measures — a slow deploy, a recompile
storm, or a degraded host was only caught if someone re-ran a bench.
The source paper's compile-once bet (trace once, re-execute every
iteration) means each executable has a STABLE per-iteration cost:
exactly the invariant an online detector can baseline per HLO
fingerprint. Three cooperating pieces:

  1. `BaselineStore` — robust per-signal latency baselines (median/MAD
     over a warmup window) annotated with introspect's abstract-
     signature HLO fingerprint, persisted as JSONL so a restarted
     process compares its executables against the PREVIOUS
     incarnation's baselines. The fingerprint is deterministic
     (sha256 of key + abstract signature), so the same model at the
     same shapes hashes identically across restarts — a fingerprint-
     matched baseline that froze `restart_factor`x slower than its
     predecessor is a cross-restart regression (a slow deploy),
     convicted at freeze time. In-session detection compares against
     the FROZEN baseline regardless of the current fingerprint:
     fingerprint drift mid-run is compile-cause evidence, not a reason
     to forget what "fast" looked like.

  2. `RegressionDetector` — online change-point detection over the
     fenced signals that already exist, fed by listeners (no new
     instrumentation on the hot paths): `model.step` span durations,
     engine decode-sync (`serving.engine_step`) and per-bucket prefill
     spans, and request TTFT / inter-token latency derived from the
     engine's terminal-request stream (`slo.request_latency_sample`;
     synthetic audit probes are excluded at the door). Per signal, a
     windowed CUSUM: z = (window_median - baseline_median) / sigma
     with sigma = max(MAD * 1.4826, rel_floor * median), z capped so a
     single wild window cannot run the score away, S = max(0, S + z -
     k), and a conviction only after S > h for `sustain` consecutive
     windows (the house sustained-verdict hysteresis). An episode
     recovers when z falls back under `recover_z` for
     `recover_sustain` windows.

  3. Cause attribution — a conviction names a cause from
     `REGRESS_CAUSES`, checked in order:
       compile         a recompile-blame record fired for the signal's
                       AOT key since its baseline froze, or the
                       manifest's newest fingerprint no longer matches
                       the baseline's
       host            the fleet aggregator's `fleet_regress` shard
                       lines vote exactly ONE host regressed (>= 3
                       voters): hardware suspect; a fleet-wide vote is
                       software and falls through
       workload_shift  the prefill-bucket mix, occupancy, or output-
                       length mix since the freeze drifted from the
                       warmup window's
       contention      the admission queue rose well past its freeze
                       level, or the goodput ratio fell / data_wait
                       share rose (training side)
       unknown         none of the above produced evidence

Each conviction auto-captures an evidence bundle
`flight_regress_<n>.jsonl` in the FlightRecorder line format
(/flightz-indexed, `load_flight_bundle` round-trips it): a header with
the verdict, baseline, executable manifest + blame tail, goodput and
memory snapshots; one `flight_step` line per recent raw sample; the
event-ring tail as `flight_event` lines. With `profile=True` an async
`singa-regress-profile-*` thread additionally captures an on-demand
xplane trace and appends an `xprof.top_ops` table plus a
`diff_op_tables` diff against the op table captured at baseline-freeze
time.

Surfaces: `/regressz` (+`?json=1`) on the diag server, `== regress ==`
on /statusz, a `fleet_regress` shard line + the /fleetz regression
block, `singa_regress_*` metrics with enum-checked `cause=` labels,
health-note KIND_REGRESSION (the note is NOT telemetry — it survives
observe.enable(False), the audit precedent), and
`python -m singa_tpu.regress --ab`: two injected legs via existing
fault points — a sustained engine-step delay that must convict
`contention`, and a forced retrace (batch-size switch) that must
convict `compile` — gated on detection latency <= 5 windows and zero
clean-arm false positives -> REGRESS_r01.json.

Threads are named `singa-regress-*` (the conftest leak assert keys on
the prefix); `reset()` is the test-teardown contract (detector
uninstalled, listeners detached, baseline store closed).
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from collections import deque

from . import observe

#: the cause enum — the `cause=` label on singa_regress_verdicts_total
#: (lint rule 5)
REGRESS_CAUSES = ("compile", "workload_shift", "contention", "host",
                  "unknown")

CAUSE_COMPILE = "compile"
CAUSE_WORKLOAD_SHIFT = "workload_shift"
CAUSE_CONTENTION = "contention"
CAUSE_HOST = "host"
CAUSE_UNKNOWN = "unknown"


_metrics_cache = None


def _metrics():
    # memoize-with-revalidation (engine._metrics's shape): cheap on the
    # span-listener path, rebuilt after a conftest registry reset
    global _metrics_cache
    c = _metrics_cache
    if c is not None and observe.get_registry().get(
            "singa_regress_windows_total") is c["windows"]:
        return c
    _metrics_cache = c = {
        "windows": observe.counter(
            "singa_regress_windows_total",
            "closed change-point detection windows across all "
            "regression signals"),
        "verdicts": observe.counter(
            "singa_regress_verdicts_total",
            "sustained regression convictions, by attributed cause"),
        "recoveries": observe.counter(
            "singa_regress_recoveries_total",
            "regression episodes that recovered (window latency back "
            "under the baseline band for recover_sustain windows)"),
        "bundles": observe.counter(
            "singa_regress_bundles_total",
            "flight_regress_<n>.jsonl evidence bundles written"),
        "baselines": observe.gauge(
            "singa_regress_baselines",
            "signals with a frozen latency baseline"),
        "active": observe.gauge(
            "singa_regress_active_episodes",
            "signals currently inside an unrecovered regression "
            "episode"),
        "score": observe.gauge(
            "singa_regress_score",
            "current CUSUM score per signal (S = max(0, S + z - k); a "
            "conviction needs S > h for sustain consecutive windows)"),
    }
    return c


# ---- robust statistics ------------------------------------------------------

def _median(xs):
    s = sorted(xs)
    n = len(s)
    if not n:
        return 0.0
    return s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])


def _mad(xs, med):
    return _median([abs(x - med) for x in xs])


# ---- signal <-> executable mapping ------------------------------------------

def _introspect_keys(signal: str) -> tuple:
    """The introspect AOT key(s) whose HLO fingerprint anchors a
    signal's baseline. Request-level signals have no executable of
    their own; they inherit the serving executables (a prefill or
    decode recompile moves TTFT/ITL)."""
    if signal.startswith("model.step"):
        return ("step",)
    if signal == "engine.step":
        return ("serving.engine_step", "serving.engine_spec_step")
    if signal.startswith("engine.prefill"):
        return ("serving.engine_prefill", "serving.engine_spec_prefill")
    if signal.startswith("request."):
        return ("serving.engine_step", "serving.engine_prefill",
                "serving.engine_spec_step", "serving.engine_spec_prefill")
    return ()


def _fingerprint_of(signal: str) -> "str | None":
    try:
        from . import introspect
        for k in _introspect_keys(signal):
            fp = introspect.latest_fingerprint(k)
            if fp:
                return fp
    except Exception:
        pass
    return None


# ---- piece 1: the baseline store --------------------------------------------

class BaselineStore:
    """Per-signal robust latency baselines with JSONL persistence.

    Keys are SIGNAL NAMES; each frozen entry carries the signal's
    newest HLO fingerprint as metadata. `path` (optional) is read at
    construction — the last persisted entry per signal becomes the
    PRIOR-incarnation baseline — then opened for append, so every
    freeze this process performs lands on disk for the NEXT
    incarnation. `restart_regression` compares a just-frozen entry
    against the prior one: a verdict only when the fingerprints MATCH
    (same executable — a changed fingerprint is a different program,
    not a regression of this one) and the fresh median exceeds
    `restart_factor` x the old."""

    def __init__(self, path=None, *, restart_factor=1.5):
        self.path = path
        self.restart_factor = float(restart_factor)
        self._lock = threading.Lock()
        self._entries: "dict[str, dict]" = {}
        self._prior: "dict[str, dict]" = {}
        self._fh = None
        if path:
            self._prior = self._load(path)
            try:
                self._fh = open(path, "a", encoding="utf-8")
            except OSError:
                self._fh = None

    @staticmethod
    def _load(path) -> dict:
        prior = {}
        try:
            with open(path, encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    if isinstance(rec, dict) \
                            and rec.get("kind") == "baseline" \
                            and rec.get("signal"):
                        prior[rec["signal"]] = rec  # last line wins
        except OSError:
            pass
        return prior

    def freeze(self, signal: str, samples, fingerprint=None) -> dict:
        """Freeze one signal's baseline from its warmup samples and
        persist it. Returns the entry."""
        med = _median(samples)
        entry = {
            "kind": "baseline", "signal": signal,
            "median_s": round(med, 9),
            "mad_s": round(_mad(samples, med), 9),
            "n": len(samples), "fingerprint": fingerprint,
            "pid": os.getpid(), "ts": round(time.time(), 6),
        }
        with self._lock:
            self._entries[signal] = entry
            if self._fh is not None:
                try:
                    self._fh.write(
                        json.dumps(entry, sort_keys=True) + "\n")
                    self._fh.flush()
                except Exception:
                    pass
        return dict(entry)

    def get(self, signal: str) -> "dict | None":
        with self._lock:
            e = self._entries.get(signal)
            return dict(e) if e else None

    def prior(self, signal: str) -> "dict | None":
        e = self._prior.get(signal)
        return dict(e) if e else None

    def restart_regression(self, entry: dict) -> "dict | None":
        """Cross-restart check for a just-frozen entry: the previous
        incarnation's persisted baseline for the same signal AND the
        same fingerprint, when this incarnation froze restart_factor x
        slower. Returns {"prior", "ratio"} or None."""
        p = self.prior(entry.get("signal") or "")
        if not p:
            return None
        fp_old, fp_new = p.get("fingerprint"), entry.get("fingerprint")
        if not fp_old or not fp_new or fp_old != fp_new:
            return None  # different executable: not comparable
        old = float(p.get("median_s") or 0.0)
        new = float(entry.get("median_s") or 0.0)
        if old <= 0.0 or new <= self.restart_factor * old:
            return None
        return {"prior": p, "ratio": round(new / old, 4)}

    def baselines(self) -> "list[dict]":
        with self._lock:
            return [dict(e) for e in self._entries.values()]

    def close(self):
        fh = self._fh
        self._fh = None
        if fh is not None:
            try:
                fh.close()
            except Exception:
                pass


# ---- per-signal detection state ---------------------------------------------

class _Signal:
    __slots__ = ("name", "warm", "window", "recent", "baseline",
                 "cusum", "z", "streak", "recover_streak", "windows",
                 "samples", "tainted", "episode", "verdicts", "env0",
                 "mix0", "last_window_median")

    def __init__(self, name):
        self.name = name
        self.warm = []
        self.window = []
        self.recent = deque(maxlen=128)  # raw samples for the bundle
        self.baseline = None
        self.cusum = 0.0
        self.z = None
        self.streak = 0
        self.recover_streak = 0
        self.windows = 0
        self.samples = 0
        self.tainted = 0
        self.episode = None
        self.verdicts = 0
        self.env0 = None
        self.mix0 = None
        self.last_window_median = None


# ---- piece 2+3: the detector ------------------------------------------------

class RegressionDetector:
    """Online change-point detection over the runtime's fenced latency
    signals, with cause attribution and evidence-bundle capture. See
    the module docstring for the math; the knobs:

    warmup_samples  raw samples frozen into the baseline (median/MAD)
    window          samples per detection window (the CUSUM consumes
                    window MEDIANS, so a single straggler sample
                    cannot advance the score)
    k / h           CUSUM drift allowance and decision threshold
    sustain         consecutive S > h windows before a conviction
    z_cap           per-window z ceiling (bounds S growth per window,
                    so detection latency is readable: a total outage
                    still takes `sustain` windows, not one)
    rel_floor       sigma floor as a fraction of the baseline median
                    (MAD of a quiet warmup can be ~0; a 5% floor keeps
                    z finite and calibrated to relative change)
    recover_z /     episode recovery: z at or under recover_z for
    recover_sustain recover_sustain consecutive windows
    profile         capture xplane op tables (baseline at freeze,
                    regressed at conviction) on async
                    `singa-regress-profile-*` threads and append the
                    diff_op_tables diff to the bundle
    """

    _seq = 0
    _seq_lock = threading.Lock()

    def __init__(self, store: "BaselineStore | None" = None, *,
                 warmup_samples=24, window=8, k=0.5, h=4.0, sustain=2,
                 z_cap=8.0, rel_floor=0.05, min_sigma_s=2e-5,
                 recover_z=1.0, recover_sustain=2, mix_drift=0.3,
                 out_len_ratio=1.3, out_dir=".", bundle_events=64,
                 max_signals=64, profile=False, profile_s=0.4):
        self.store = store or BaselineStore()
        self.warmup_samples = int(warmup_samples)
        self.window = int(window)
        self.k = float(k)
        self.h = float(h)
        self.sustain = int(sustain)
        self.z_cap = float(z_cap)
        self.rel_floor = float(rel_floor)
        self.min_sigma_s = float(min_sigma_s)
        self.recover_z = float(recover_z)
        self.recover_sustain = int(recover_sustain)
        self.mix_drift = float(mix_drift)
        self.out_len_ratio = float(out_len_ratio)
        self.out_dir = str(out_dir)
        self.bundle_events = int(bundle_events)
        self.max_signals = int(max_signals)
        self.profile = bool(profile)
        self.profile_s = float(profile_s)
        self._lock = threading.Lock()
        self._signals: "dict[str, _Signal]" = {}
        self._verdicts: "deque[dict]" = deque(maxlen=64)
        self._bundle_seq = 0
        self._bundles: "list[str]" = []
        self._threads: "list[threading.Thread]" = []
        self._baseline_ops = None  # op table captured at first freeze
        # cumulative workload-mix counters (the drift comparisons use
        # pre-freeze vs post-freeze deltas, so cumulative is enough)
        self._mix_buckets: "dict[int, int]" = {}
        self._mix_out_tokens = 0
        self._mix_out_n = 0
        self._mix_slots_sum = 0.0
        self._mix_slots_n = 0
        self._recent_queue: "deque[float]" = deque(maxlen=32)
        self._installed = False

    # -- lifecycle ---------------------------------------------------------
    def install(self) -> "RegressionDetector":
        """Register as the process detector (module singleton — the
        diag/fleet surfaces and the conftest teardown find it) and
        attach the span + engine request listeners."""
        install(self)
        if not self._installed:
            observe.add_span_listener(self._on_span)
            try:
                from . import engine
                engine.add_request_listener(self._on_request)
            except Exception:
                pass  # no serving stack in this process
            self._installed = True
        return self

    def uninstall(self):
        """Detach the listeners, join any profile threads, close the
        baseline store, drop the module registration if it points
        here. Idempotent."""
        if self._installed:
            observe.remove_span_listener(self._on_span)
            try:
                from . import engine
                engine.remove_request_listener(self._on_request)
            except Exception:
                pass
            self._installed = False
        for t in self._threads:
            t.join(timeout=10.0)
        self._threads = []
        self.store.close()
        global _detector
        with _registry_lock:
            if _detector is self:
                _detector = None

    # -- feeding -----------------------------------------------------------
    def _on_span(self, path, seconds, attrs):
        """observe span listener. Children exit before parents, so a
        nested jit-fallback build taints the enclosing step sample
        BEFORE that sample arrives — first-compile time neither
        convicts nor calibrates."""
        leaf = path.rsplit("/", 1)[-1]
        if leaf in ("model.jit_fallback", "introspect.build") \
                and "/" in path:
            parent = path.rsplit("/", 2)[-2]
            sig = self._signal_of(parent, {})
            if sig is not None:
                with self._lock:
                    st = self._signals.get(sig)
                    if st is not None:
                        st.tainted += 1
            return
        signal = self._signal_of(leaf, attrs or {})
        if signal is None:
            return
        if leaf == "serving.engine_step":
            q = (attrs or {}).get("queue")
            if q is not None:
                self._recent_queue.append(float(q))
            s = (attrs or {}).get("slots")
            if s:
                self._mix_slots_sum += float(s)
                self._mix_slots_n += 1
        elif leaf == "serving.engine_prefill":
            b = (attrs or {}).get("bucket")
            if b is not None:
                self._mix_buckets[int(b)] = \
                    self._mix_buckets.get(int(b), 0) + 1
        self.feed(signal, seconds)

    @staticmethod
    def _signal_of(leaf, attrs) -> "str | None":
        if leaf == "model.step":
            tag = attrs.get("tag")
            return "model.step" if not tag else f"model.step.t{tag}"
        if leaf == "serving.engine_step":
            return "engine.step"
        if leaf == "serving.engine_prefill":
            b = attrs.get("bucket")
            return f"engine.prefill.{b}" if b is not None \
                else "engine.prefill"
        return None

    def _on_request(self, req, timeline):
        """engine request listener: TTFT + mean inter-token latency per
        COMPLETED real request (synthetic audit probes excluded inside
        slo.request_latency_sample)."""
        try:
            from . import slo
            sample = slo.request_latency_sample(req, timeline)
        except Exception:
            return
        if sample is None:
            return
        toks = sample.get("tokens") or 0
        if toks:
            self._mix_out_tokens += int(toks)
            self._mix_out_n += 1
        if sample.get("ttft_s") is not None:
            self.feed("request.ttft", float(sample["ttft_s"]))
        if sample.get("itl_s") is not None:
            self.feed("request.itl", float(sample["itl_s"]))

    def feed(self, signal: str, seconds: float):
        """One raw latency sample for `signal` — the listener entry
        point, also driven directly by tests and bench.py --regress."""
        with self._lock:
            sig = self._signals.get(signal)
            if sig is None:
                if len(self._signals) >= self.max_signals:
                    return
                sig = self._signals[signal] = _Signal(signal)
            if sig.tainted > 0:
                sig.tainted -= 1
                return
            sig.samples += 1
            sig.recent.append(round(float(seconds), 9))
            if sig.baseline is None:
                sig.warm.append(float(seconds))
                if len(sig.warm) >= self.warmup_samples:
                    self._freeze_locked(sig)
                return
            sig.window.append(float(seconds))
            if len(sig.window) < self.window:
                return
            self._close_window_locked(sig)

    def _freeze_locked(self, sig: _Signal):
        fp = _fingerprint_of(sig.name)
        entry = self.store.freeze(sig.name, sig.warm, fingerprint=fp)
        sig.baseline = entry
        sig.warm = []
        sig.env0 = self._env_snapshot()
        sig.mix0 = self._mix_snapshot()
        if observe.is_enabled():
            _metrics()["baselines"].set(float(sum(
                1 for s in self._signals.values()
                if s.baseline is not None)))
        if self.profile and self._baseline_ops is None:
            self._baseline_ops = ()  # claimed: one capture per process
            self._spawn_profile("baseline", None)
        # cross-restart check: the PREVIOUS incarnation persisted a
        # baseline for this signal at this fingerprint — freezing
        # restart_factor x slower is a slow deploy, convicted now
        rr = self.store.restart_regression(entry)
        if rr is not None:
            self._convict_locked(sig, float(entry["median_s"]),
                                 restart=rr)

    def _close_window_locked(self, sig: _Signal):
        med = _median(sig.window)
        sig.window = []
        sig.windows += 1
        sig.last_window_median = med
        base = sig.baseline
        sigma = max(float(base["mad_s"]) * 1.4826,
                    self.rel_floor * float(base["median_s"]),
                    self.min_sigma_s)
        z = (med - float(base["median_s"])) / sigma
        sig.z = round(min(z, self.z_cap), 4)
        sig.cusum = max(0.0, sig.cusum + sig.z - self.k)
        if observe.is_enabled():
            m = _metrics()
            m["windows"].inc()
            m["score"].set(round(sig.cusum, 4), signal=sig.name)
        if sig.episode is None:
            sig.streak = sig.streak + 1 if sig.cusum > self.h else 0
            if sig.streak >= self.sustain:
                self._convict_locked(sig, med)
        else:
            if z <= self.recover_z:
                sig.recover_streak += 1
                if sig.recover_streak >= self.recover_sustain:
                    self._recover_locked(sig, med)
            else:
                sig.recover_streak = 0

    # -- conviction / recovery ---------------------------------------------
    def _convict_locked(self, sig: _Signal, window_median: float,
                        restart: "dict | None" = None):
        now_env = self._env_snapshot()
        cause, evidence = self._attribute_locked(sig, now_env)
        base = restart["prior"] if restart is not None else sig.baseline
        base_med = float(base.get("median_s") or 0.0)
        rec = {
            "kind": "regress_verdict", "ts": round(time.time(), 6),
            "signal": sig.name, "cause": cause,
            "restart": restart is not None,
            "baseline_median_s": base_med,
            "window_median_s": round(window_median, 9),
            "ratio": round(window_median / max(base_med, 1e-12), 4),
            "z": sig.z, "cusum": round(sig.cusum, 4),
            "window": sig.windows, "samples": sig.samples,
            "fingerprint": _fingerprint_of(sig.name),
            "baseline_fingerprint": sig.baseline.get("fingerprint"),
            "evidence": evidence,
        }
        sig.episode = {"signal": sig.name, "cause": cause,
                       "ts": rec["ts"], "window": sig.windows}
        sig.verdicts += 1
        sig.streak = 0
        sig.recover_streak = 0
        try:
            rec["bundle"] = self._capture_bundle_locked(rec, sig,
                                                        now_env)
        except Exception:
            rec["bundle"] = None  # forensics must not break detection
        self._record_verdict(rec)
        if self.profile:
            self._spawn_profile("regressed", rec["bundle"])

    def _recover_locked(self, sig: _Signal, window_median: float):
        episode = sig.episode
        sig.episode = None
        sig.cusum = 0.0
        sig.streak = 0
        sig.recover_streak = 0
        if observe.is_enabled():
            m = _metrics()
            m["recoveries"].inc()
            m["active"].set(float(sum(
                1 for s in self._signals.values()
                if s.episode is not None)))
            m["score"].set(0.0, signal=sig.name)
            observe.get_registry().emit({
                "kind": "regress_recovery", "signal": sig.name,
                "cause": (episode or {}).get("cause"),
                "window_median_s": round(window_median, 9),
                "window": sig.windows})

    def _record_verdict(self, rec: dict):
        assert rec["cause"] in REGRESS_CAUSES, rec["cause"]
        self._verdicts.append(rec)
        # the event-stream mirror is telemetry (honors
        # observe.enable(False)); the ring above is detector state
        observe.record_regress_verdict(rec)
        if observe.is_enabled():
            m = _metrics()
            m["verdicts"].inc(cause=rec["cause"])
            m["active"].set(float(sum(
                1 for s in self._signals.values()
                if s.episode is not None)))
        # the health note is NOT telemetry: it survives
        # observe.enable(False) so /healthz cannot claim a healthy
        # process the detector just convicted (the audit precedent)
        try:
            from . import health
            mon = health.active_monitor()
            if mon is not None:
                mon.note_external(
                    health.KIND_REGRESSION,
                    detail={"signal": rec["signal"],
                            "cause": rec["cause"],
                            "ratio": rec["ratio"],
                            "restart": rec["restart"]},
                    action="warn")
        except Exception:
            pass  # the monitor must not break the detection path

    # -- cause attribution --------------------------------------------------
    def _attribute_locked(self, sig: _Signal, now_env: dict):
        """(cause, evidence) for a conviction, checked in precedence
        order: compile -> host -> workload_shift -> contention ->
        unknown."""
        ev: dict = {}
        # compile: a recompile blame for this signal's AOT key since
        # the baseline froze, or a fingerprint that drifted from it
        try:
            from . import introspect
            keys = _introspect_keys(sig.name)
            frozen_ts = float((sig.baseline or {}).get("ts") or 0.0)
            blames = [b for b in introspect.blame_history()
                      if float(b.get("ts") or 0.0) >= frozen_ts
                      and (not keys or b.get("key") in keys)]
            fp_now = _fingerprint_of(sig.name)
            base_fp = (sig.baseline or {}).get("fingerprint")
            fp_changed = bool(base_fp and fp_now and fp_now != base_fp)
            if blames or fp_changed:
                ev["blames"] = [
                    {k: b.get(k) for k in ("key", "reason", "detail",
                                           "fingerprint")}
                    for b in blames[-4:]]
                ev["fingerprint_changed"] = fp_changed
                return CAUSE_COMPILE, ev
        except Exception:
            pass
        # host: the coordinator's shard vote localizes the regression
        vote = fleet_regress_vote()
        if vote is not None:
            ev["fleet_vote"] = vote
            if vote.get("verdict") == "host":
                return CAUSE_HOST, ev
        # workload shift: serving-side mix drift vs the warmup window
        shift = self._mix_shift(sig)
        if shift is not None:
            ev["mix"] = shift
            if shift.get("shifted"):
                return CAUSE_WORKLOAD_SHIFT, ev
        # contention: the environment got worse at fixed work
        ev["env"] = {"frozen": sig.env0, "now": now_env}
        if self._contended(sig.env0 or {}, now_env or {}):
            return CAUSE_CONTENTION, ev
        return CAUSE_UNKNOWN, ev

    def _mix_snapshot(self) -> dict:
        return {"buckets": dict(self._mix_buckets),
                "out_tokens": self._mix_out_tokens,
                "out_n": self._mix_out_n,
                "slots_sum": self._mix_slots_sum,
                "slots_n": self._mix_slots_n}

    def _mix_shift(self, sig: _Signal) -> "dict | None":
        """Workload-mix drift since the freeze, for serving signals:
        total-variation distance between the pre-freeze and
        post-freeze prefill-bucket distributions, plus output-length
        and occupancy ratios. None for signals with no workload mix
        (model.step) or before enough mass on both sides."""
        if not (sig.name.startswith("engine.")
                or sig.name.startswith("request.")):
            return None
        f = sig.mix0
        if f is None:
            return None
        cur = self._mix_snapshot()
        pre_b = f.get("buckets") or {}
        post_b = {b: cur["buckets"].get(b, 0) - pre_b.get(b, 0)
                  for b in set(cur["buckets"]) | set(pre_b)}
        n_pre, n_post = sum(pre_b.values()), sum(post_b.values())
        drift = None
        if n_pre >= 8 and n_post >= 8:
            drift = round(0.5 * sum(
                abs(pre_b.get(b, 0) / n_pre - post_b.get(b, 0) / n_post)
                for b in set(pre_b) | set(post_b)), 4)
        out_ratio = None
        d_n = cur["out_n"] - f["out_n"]
        if f["out_n"] >= 4 and d_n >= 4:
            pre = f["out_tokens"] / f["out_n"]
            post = (cur["out_tokens"] - f["out_tokens"]) / d_n
            out_ratio = round(post / max(pre, 1e-9), 4)
        occ_ratio = None
        d_s = cur["slots_n"] - f["slots_n"]
        if f["slots_n"] >= 4 and d_s >= 4:
            pre = f["slots_sum"] / f["slots_n"]
            post = (cur["slots_sum"] - f["slots_sum"]) / d_s
            occ_ratio = round(post / max(pre, 1e-9), 4)
        r = self.out_len_ratio
        shifted = bool(
            (drift is not None and drift > self.mix_drift)
            or (out_ratio is not None
                and not (1.0 / r <= out_ratio <= r))
            or (occ_ratio is not None
                and not (1.0 / r <= occ_ratio <= r)))
        return {"bucket_drift": drift, "out_len_ratio": out_ratio,
                "occupancy_ratio": occ_ratio, "shifted": shifted}

    def _env_snapshot(self) -> dict:
        env = {"queue_depth": None, "slots": None, "span_queue": None,
               "goodput_ratio": None, "data_wait_frac": None}
        try:
            from . import slo as slo_mod
            s = slo_mod.fleet_serve_snapshot(max_timelines=0,
                                             max_syncs=0)
            if s is not None:
                env["queue_depth"] = s.get("queue_depth")
                env["slots"] = s.get("slots")
        except Exception:
            pass
        try:
            from . import goodput
            tr = goodput.get_tracker()
            if tr is not None:
                gs = tr.snapshot()
                env["goodput_ratio"] = round(
                    float(gs.get("window_goodput_ratio")
                          or gs.get("goodput_ratio") or 0.0), 4)
                wall = float(gs.get("wall_s") or 0.0)
                if wall > 0:
                    env["data_wait_frac"] = round(float(
                        (gs.get("buckets") or {}).get("data_wait", 0.0)
                    ) / wall, 4)
        except Exception:
            pass
        if self._recent_queue:
            env["span_queue"] = round(
                _median(list(self._recent_queue)), 2)
        return env

    def _contended(self, frozen: dict, now: dict) -> bool:
        # in-band queue from the engine_step span attrs first, then
        # the polled snapshot; then the training-side goodput signals
        for key in ("span_queue", "queue_depth"):
            q0, q1 = frozen.get(key), now.get(key)
            if q1 is not None and float(q1) >= max(
                    2.0, 2.0 * float(q0 or 0.0),
                    float(q0 or 0.0) + float(now.get("slots") or 2.0)):
                return True
        g0, g1 = frozen.get("goodput_ratio"), now.get("goodput_ratio")
        if g0 is not None and g1 is not None \
                and float(g0) - float(g1) > 0.15:
            return True
        d0, d1 = frozen.get("data_wait_frac"), now.get("data_wait_frac")
        if d1 is not None and float(d1) - float(d0 or 0.0) > 0.10:
            return True
        return False

    # -- the evidence bundle -------------------------------------------------
    def _capture_bundle_locked(self, rec: dict, sig: _Signal,
                               now_env: dict) -> str:
        """Write flight_regress_<n>.jsonl in the FlightRecorder line
        format (flight_header / flight_step / flight_event) so
        /flightz indexes it and health.load_flight_bundle round-trips
        it."""
        os.makedirs(self.out_dir, exist_ok=True)
        self._bundle_seq += 1
        path = os.path.join(self.out_dir,
                            f"flight_regress_{self._bundle_seq}.jsonl")
        tail = list(observe.get_registry().recent)[-self.bundle_events:]
        execs = blames = None
        try:
            from . import introspect
            execs = introspect.executable_manifest()[-8:] or None
            blames = introspect.blame_history()[-8:] or None
        except Exception:
            pass
        gp = mem = None
        try:
            from . import goodput
            tr = goodput.get_tracker()
            gp = tr.snapshot() if tr is not None else None
        except Exception:
            pass
        try:
            from . import memory
            led = memory.get_ledger()
            mem = led.region_bytes() if led is not None else None
        except Exception:
            pass
        header = {
            "kind": "flight_header", "ts": rec["ts"],
            "reason": "regression", "step": sig.windows,
            "signal": sig.name, "cause": rec["cause"],
            "verdict": {k: rec[k] for k in
                        ("signal", "cause", "restart",
                         "baseline_median_s", "window_median_s",
                         "ratio", "z", "cusum", "window",
                         "fingerprint", "baseline_fingerprint")},
            "n_steps": len(sig.recent), "n_events": len(tail),
            "batch_snapshot": None,
            "executables": execs, "blames": blames,
            "baseline": sig.baseline, "goodput": gp, "memory": mem,
            "env": {"frozen": sig.env0, "now": now_env},
        }
        with open(path, "w", encoding="utf-8") as f:
            f.write(json.dumps(header, separators=(",", ":"),
                               default=str) + "\n")
            for i, s in enumerate(sig.recent):
                f.write(json.dumps(
                    {"kind": "flight_step", "i": i,
                     "signal": sig.name, "seconds": s},
                    separators=(",", ":")) + "\n")
            for ev in tail:
                # nested, not splatted: the event's own "kind" must
                # not clobber the line marker (FlightRecorder's rule)
                f.write(json.dumps({"kind": "flight_event",
                                    "event": ev},
                                   separators=(",", ":"),
                                   default=str) + "\n")
        self._bundles.append(path)
        if observe.is_enabled():
            _metrics()["bundles"].inc()
        return path

    # -- optional xplane capture ---------------------------------------------
    def _spawn_profile(self, tag: str, bundle_path: "str | None"):
        with RegressionDetector._seq_lock:
            RegressionDetector._seq += 1
            n = RegressionDetector._seq
        t = threading.Thread(
            target=self._profile_main, args=(tag, bundle_path),
            name=f"singa-regress-profile-{n}", daemon=True)
        self._threads.append(t)
        t.start()

    def _profile_main(self, tag: str, bundle_path: "str | None"):
        table = self._profile_capture()
        if table is None:
            return
        if tag == "baseline":
            self._baseline_ops = table
            return
        # regressed capture: append the top-ops diff to the bundle as
        # one more flight_event line (the JSONL format appends cleanly;
        # load_flight_bundle picks it up on the next read)
        try:
            from . import xprof
            base = self._baseline_ops or []
            event = {"kind": "regress_profile", "tag": tag,
                     "top_ops": xprof.top_ops(table, 10),
                     "op_diff": xprof.diff_op_tables(base, table)[:10]
                     if base else None}
            if bundle_path:
                with open(bundle_path, "a", encoding="utf-8") as f:
                    f.write(json.dumps(
                        {"kind": "flight_event", "event": event},
                        separators=(",", ":"), default=str) + "\n")
        except Exception:
            pass

    def _profile_capture(self) -> "list | None":
        """One bounded on-demand xplane capture -> op_table rows, or
        None when the process-global profiler is busy (/profilez's
        guard) or tracing is unavailable."""
        import shutil
        import tempfile
        out = tempfile.mkdtemp(prefix="singa_regress_prof_")
        try:
            from .device import get_default_device
            dev = get_default_device()
            dev.StartTrace(out)
        except Exception:
            shutil.rmtree(out, ignore_errors=True)
            return None
        try:
            time.sleep(self.profile_s)
        finally:
            try:
                dev.StopTrace()
            except Exception:
                pass
        try:
            from . import xprof
            rows = xprof.op_table(out)
        except Exception:
            rows = None
        shutil.rmtree(out, ignore_errors=True)
        return rows

    # -- introspection -------------------------------------------------------
    def verdicts(self) -> "list[dict]":
        with self._lock:
            return [dict(r) for r in self._verdicts]

    def bundles(self) -> "list[str]":
        with self._lock:
            return list(self._bundles)

    def signal_state(self, signal: str) -> "dict | None":
        with self._lock:
            sig = self._signals.get(signal)
            return self._row_locked(sig) if sig is not None else None

    @staticmethod
    def _row_locked(sig: _Signal) -> dict:
        base = sig.baseline or {}
        return {
            "signal": sig.name, "samples": sig.samples,
            "windows": sig.windows,
            "baseline_median_s": base.get("median_s"),
            "baseline_mad_s": base.get("mad_s"),
            "fingerprint": base.get("fingerprint"),
            "window_median_s": sig.last_window_median,
            "z": sig.z, "cusum": round(sig.cusum, 4),
            "streak": sig.streak, "verdicts": sig.verdicts,
            "state": ("warmup" if sig.baseline is None
                      else "REGRESSED" if sig.episode is not None
                      else "ok"),
        }

    def snapshot(self) -> dict:
        with self._lock:
            rows = [self._row_locked(s)
                    for s in self._signals.values()]
            return {
                "signals": rows,
                "n_signals": len(rows),
                "baselines": sum(1 for r in rows
                                 if r["baseline_median_s"] is not None),
                "active": [r["signal"] for r in rows
                           if r["state"] == "REGRESSED"],
                "windows": sum(r["windows"] for r in rows),
                "verdicts": len(self._verdicts),
                "last_verdict": dict(self._verdicts[-1])
                if self._verdicts else None,
                "bundles": list(self._bundles),
                "store_path": self.store.path,
                "config": {
                    "warmup_samples": self.warmup_samples,
                    "window": self.window, "k": self.k, "h": self.h,
                    "sustain": self.sustain, "z_cap": self.z_cap,
                    "rel_floor": self.rel_floor,
                    "recover_z": self.recover_z,
                    "recover_sustain": self.recover_sustain,
                    "restart_factor": self.store.restart_factor,
                },
            }


# ---- module singleton (the conftest teardown contract) ---------------------

_detector: "RegressionDetector | None" = None
_registry_lock = threading.Lock()


def install(det: RegressionDetector) -> RegressionDetector:
    global _detector
    with _registry_lock:
        prev = _detector
        _detector = det
    if prev is not None and prev is not det:
        prev.uninstall()
    return det


def get_detector() -> "RegressionDetector | None":
    return _detector


def uninstall():
    global _detector
    with _registry_lock:
        d = _detector
        _detector = None
    if d is not None:
        d.uninstall()


def reset():
    """Test-teardown contract: detector uninstalled (listeners
    detached, profile threads joined, baseline store closed)."""
    uninstall()


# ---- the fleet shard line / vote --------------------------------------------

def fleet_regress_snapshot() -> "dict | None":
    """The `fleet_regress` shard line: this replica's detector rollup —
    baseline/episode counts and the last verdict — compact enough to
    ride every shard write. None without a detector."""
    det = get_detector()
    if det is None:
        return None
    snap = det.snapshot()
    last = snap.get("last_verdict") or None
    return {
        "signals": snap["n_signals"],
        "baselines": snap["baselines"],
        "active": len(snap["active"]),
        "active_signals": snap["active"][:4],
        "verdicts": snap["verdicts"],
        "windows": snap["windows"],
        "last": {k: last.get(k) for k in ("signal", "cause", "ratio",
                                          "restart", "ts")}
        if last else None,
    }


def fleet_regress_vote() -> "dict | None":
    """The coordinator's localization vote over the workers'
    `fleet_regress` shard lines: with >= 3 fresh voters, exactly ONE
    worker inside an active episode is a host-localized regression
    (hardware suspect); a strict majority regressed is fleet-wide
    (software). None without an aggregator, under 3 voters, or no
    clear verdict."""
    try:
        from . import fleet
        agg = fleet.get_aggregator()
        if agg is None:
            return None
        rows = agg.rollup()["workers"]
    except Exception:
        return None
    voters = [r for r in rows
              if isinstance(r.get("regress"), dict)
              and not r.get("stale")]
    if len(voters) < 3:
        return None
    regressed = sorted(r["host"] for r in voters
                       if (r["regress"].get("active") or 0) > 0)
    verdict = None
    if len(regressed) == 1:
        verdict = "host"
    elif len(regressed) > len(voters) // 2:
        verdict = "software"
    if verdict is None:
        return None
    return {"verdict": verdict, "voters": len(voters),
            "regressed": regressed}


def fleetz_lines() -> "list[str]":
    """The coordinator-side `== fleet regress ==` block for /fleetz:
    one row per worker shard that published a `fleet_regress` line,
    plus the localization vote. [] when there is nothing to show."""
    try:
        from . import fleet
        agg = fleet.get_aggregator()
        if agg is None:
            return []
        rows = [r for r in agg.rollup()["workers"]
                if isinstance(r.get("regress"), dict)]
    except Exception:
        return []
    if not rows:
        return []
    lines = ["== fleet regress ==",
             f"{'host':<16} {'baselines':>9} {'active':>6} "
             f"{'verdicts':>8} last"]
    for r in rows:
        g = r["regress"]
        last = g.get("last") or {}
        last_s = (f"{last.get('signal')} [{last.get('cause')}] "
                  f"x{last.get('ratio')}"
                  + (" restart" if last.get("restart") else "")) \
            if last else "-"
        lines.append(
            f"{r['host']:<16} {g.get('baselines', 0):>9} "
            f"{g.get('active', 0):>6} {g.get('verdicts', 0):>8} "
            f"{last_s}"
            + (" [stale]" if r.get("stale") else ""))
    vote = fleet_regress_vote()
    if vote is not None:
        lines.append(
            f"vote: {vote['verdict']} ({len(vote['regressed'])}/"
            f"{vote['voters']} regressed: "
            + (", ".join(vote["regressed"]) or "-") + ")")
    return lines


# ---- reports ----------------------------------------------------------------

def _fmt_ms(s) -> str:
    return f"{1e3 * s:.3f}" if s is not None else "-"


def regress_report() -> str:
    """The /regressz (and /statusz `== regress ==`) text block: the
    per-signal baseline/CUSUM table, the verdict tail, and the
    evidence-bundle index."""
    lines = ["== regress =="]
    det = get_detector()
    if det is None:
        lines.append("no RegressionDetector installed "
                     "(singa_tpu.regress.RegressionDetector(...)"
                     ".install())")
        return "\n".join(lines)
    snap = det.snapshot()
    cfg = snap["config"]
    lines.append(
        f"signals: {snap['n_signals']}  baselines "
        f"{snap['baselines']}  windows {snap['windows']}  verdicts "
        f"{snap['verdicts']}  active {len(snap['active'])}"
        f"  (window {cfg['window']}  k {cfg['k']}  h {cfg['h']}  "
        f"sustain {cfg['sustain']})")
    if snap["signals"]:
        lines.append(
            f"{'signal':<22} {'n':>6} {'base ms':>9} {'win ms':>9} "
            f"{'z':>6} {'cusum':>7} {'fp':<10} state")
        for r in sorted(snap["signals"], key=lambda r: r["signal"]):
            z = f"{r['z']:.2f}" if r["z"] is not None else "-"
            lines.append(
                f"{r['signal']:<22} {r['samples']:>6} "
                f"{_fmt_ms(r['baseline_median_s']):>9} "
                f"{_fmt_ms(r['window_median_s']):>9} "
                f"{z:>6} {r['cusum']:>7.2f} "
                f"{(r['fingerprint'] or '-')[:10]:<10} {r['state']}")
    verdicts = det.verdicts()[-6:]
    if verdicts:
        lines.append("verdicts:")
        for v in verdicts:
            lines.append(
                f"  {v['signal']}: {v['cause']}  "
                f"x{v['ratio']} (base {_fmt_ms(v['baseline_median_s'])}"
                f" -> {_fmt_ms(v['window_median_s'])} ms)  window "
                f"{v['window']}"
                + (" [restart]" if v.get("restart") else "")
                + (f"  bundle {os.path.basename(v['bundle'])}"
                   if v.get("bundle") else ""))
    if snap["bundles"]:
        lines.append("bundles: "
                     + ", ".join(os.path.basename(b)
                                 for b in snap["bundles"][-4:]))
    fl = fleetz_lines()
    if fl:
        lines.extend(fl)
    return "\n".join(lines)


def regress_json() -> dict:
    """The /regressz?json=1 body: the detector snapshot plus the full
    verdict ring."""
    det = get_detector()
    if det is None:
        return {"installed": False}
    return {"installed": True, "snapshot": det.snapshot(),
            "verdicts": det.verdicts()}


# ---- CLI: the injected-regression A/B ---------------------------------------
# `--ab` proves the whole loop end to end on one process, twice:
#
#   leg 1 (serving / contention): a tiny ServingEngine under a paced
#   request stream freezes the engine.step baseline over a clean
#   window (zero verdicts = the clean arm), then a FaultPlan delay on
#   the `serving.engine_step` fault point — which sits INSIDE the
#   decode-sync span — makes every sync slower while a burst deepens
#   the admission queue. Gate: conviction within 5 windows of the
#   injection, cause=contention.
#
#   leg 2 (training / compile): a tiny Linear net trains at batch 8
#   until model.step freezes (clean windows counted), then the batch
#   switches to 64: introspect fires a recompile blame, the manifest
#   fingerprint moves, and the bigger executable is genuinely slower
#   per step. Gate: conviction within 5 windows, cause=compile.
#
# Both verdicts' evidence bundles must round-trip through
# health.load_flight_bundle. Artifact: REGRESS_r01.json (+ the
# persisted REGRESS_baselines.jsonl beside it).

def _ab_wait(det, signal, pred, timeout_s, tick):
    """Poll the detector until pred(state) or timeout; `tick()` drives
    the workload one beat. Returns the final state."""
    t0 = time.monotonic()
    st = det.signal_state(signal)
    while time.monotonic() - t0 < timeout_s:
        if st is not None and pred(st):
            return st
        tick()
        st = det.signal_state(signal)
    return st


def _ab_serving_leg(args, out_dir, store_path) -> dict:
    from . import engine as engine_mod
    from . import resilience
    from . import router as router_mod
    import numpy as np

    leg = {"name": "contention"}
    T = args.prompt_hi + args.new_tokens + 8
    m = router_mod._build_replica_model(args.vocab, args.dim,
                                        args.layers, T)
    eng = engine_mod.ServingEngine(
        m, max_slots=args.slots, page_size=8, max_ctx=T,
        queue_limit=1024).start()
    det = RegressionDetector(
        BaselineStore(store_path),
        warmup_samples=args.warmup, window=args.window, sustain=2,
        out_dir=out_dir).install()
    rng = np.random.RandomState(args.seed)

    def submit(n):
        hs = []
        for _ in range(n):
            p = rng.randint(0, args.vocab,
                            rng.randint(args.prompt_lo,
                                        args.prompt_hi)).astype(np.int32)
            hs.append(eng.submit(p, args.new_tokens))
        return hs

    def drain(hs):
        for h in hs:
            h.wait(args.timeout)

    try:
        # clean arm: keep the engine busy until the baseline freezes
        # and a few clean windows close — every verdict here is a
        # false positive
        def busy():
            drain(submit(args.slots))

        st = _ab_wait(
            det, "engine.step",
            lambda s: s["state"] != "warmup"
            and s["windows"] >= args.clean_windows,
            args.timeout, busy)
        leg["frozen"] = st is not None and st["state"] != "warmup"
        leg["clean_windows"] = (st or {}).get("windows", 0)
        leg["false_positives"] = len(det.verdicts())
        w0 = (st or {}).get("windows", 0)
        # inject: a sustained per-sync stall inside the engine_step
        # span, plus a burst that deepens the queue past its freeze
        # level — slower at the same work, with contention evidence
        resilience.install_fault_plan(
            resilience.FaultPlan().delay("serving.engine_step",
                                         args.step_delay,
                                         times=10 ** 9))
        burst = submit(args.burst)

        def refill():
            time.sleep(0.05)
            if eng.report()["queue_depth"] < args.slots:
                burst.extend(submit(args.slots * 2))

        st = _ab_wait(det, "engine.step",
                      lambda s: s["verdicts"] > leg["false_positives"],
                      args.timeout, refill)
        resilience.clear_fault_plan()
        drain(burst)
        v = next((x for x in det.verdicts()
                  if x["signal"] == "engine.step"), None)
        leg["detected"] = v is not None
        leg["detect_windows"] = (v["window"] - w0) if v else None
        leg["cause"] = v["cause"] if v else None
        leg["ratio"] = v["ratio"] if v else None
        leg["bundle"] = v.get("bundle") if v else None
        leg["verdicts"] = len(det.verdicts())
        leg["report_has_table"] = "base ms" in regress_report()
    finally:
        resilience.clear_fault_plan()
        uninstall()
        eng.stop()
        engine_mod.reset()
    return leg


def _ab_training_leg(args, out_dir, store_path) -> dict:
    from . import device, layer, model as model_mod, opt, tensor
    import numpy as np

    leg = {"name": "compile"}
    dev = device.create_cpu_device()
    # On an async backend the model.step span covers dispatch only
    # unless something fences inside it; verbosity>0 makes the step
    # block_until_ready within the span, so the detector's samples
    # measure the executable's real wall time and the retraced
    # batch_hi variant's extra cost is visible to the CUSUM.
    dev.SetVerbosity(1)
    dev.SetSkipIteration(0)

    class Net(model_mod.Model):
        def __init__(self):
            super().__init__()
            self.fc1 = layer.Linear(args.hidden)
            self.relu = layer.ReLU()
            self.fc2 = layer.Linear(8)
            self.sce = layer.SoftMaxCrossEntropy()

        def forward(self, x):
            return self.fc2(self.relu(self.fc1(x)))

        def train_one_batch(self, x, y):
            loss = self.sce(self.forward(x), y)
            self.optimizer(loss)
            return loss

    rng = np.random.RandomState(args.seed)

    def batch(n):
        x = rng.standard_normal((n, args.features)).astype(np.float32)
        y = rng.randint(0, 8, n).astype(np.int32)
        return (tensor.from_numpy(x, dev), tensor.from_numpy(y, dev))

    net = Net()
    net.set_optimizer(opt.SGD(lr=0.05))
    tx8, ty8 = batch(args.batch_lo)
    net.compile([tx8], is_train=True, use_graph=True)
    det = RegressionDetector(
        BaselineStore(store_path),
        warmup_samples=args.warmup, window=args.window, sustain=2,
        out_dir=out_dir).install()
    try:
        def step8():
            net.train_one_batch(tx8, ty8)

        st = _ab_wait(
            det, "model.step",
            lambda s: s["state"] != "warmup"
            and s["windows"] >= args.clean_windows,
            args.timeout, step8)
        leg["frozen"] = st is not None and st["state"] != "warmup"
        leg["clean_windows"] = (st or {}).get("windows", 0)
        leg["false_positives"] = len(det.verdicts())
        w0 = (st or {}).get("windows", 0)
        # inject: a batch-size switch forces a retrace — introspect
        # fires a recompile blame and the manifest fingerprint moves —
        # and the batch_hi executable is genuinely slower per step
        tx64, ty64 = batch(args.batch_hi)

        def step64():
            net.train_one_batch(tx64, ty64)

        st = _ab_wait(det, "model.step",
                      lambda s: s["verdicts"] > leg["false_positives"],
                      args.timeout, step64)
        v = next((x for x in det.verdicts()
                  if x["signal"] == "model.step"), None)
        leg["detected"] = v is not None
        leg["detect_windows"] = (v["window"] - w0) if v else None
        leg["cause"] = v["cause"] if v else None
        leg["ratio"] = v["ratio"] if v else None
        leg["bundle"] = v.get("bundle") if v else None
        leg["verdicts"] = len(det.verdicts())
    finally:
        uninstall()
    return leg


def _ab_main(args) -> int:
    from . import diag
    from . import health as health_mod
    rec = {"seed": args.seed, "ok": False}
    out_dir = os.path.dirname(os.path.abspath(args.out)) or "."
    store_path = os.path.join(out_dir, "REGRESS_baselines.jsonl")
    if os.path.exists(store_path):
        os.remove(store_path)
    diag.start_diag_server(port=0)
    try:
        # each leg gets its own bundle directory so the two detectors'
        # flight_regress_<n>.jsonl sequences cannot collide
        serving = _ab_serving_leg(
            args, os.path.join(out_dir, "REGRESS_bundles", "serving"),
            store_path)
        training = _ab_training_leg(
            args, os.path.join(out_dir, "REGRESS_bundles", "compile"),
            store_path)
        rec["serving"] = serving
        rec["training"] = training
        # the bundle contract: every conviction's bundle round-trips
        # through load_flight_bundle with the verdict in the header
        bundle_ok = False
        bpath = serving.get("bundle") or training.get("bundle")
        if bpath and os.path.isfile(bpath):
            b = health_mod.load_flight_bundle(bpath)
            bundle_ok = (
                b["header"].get("kind") == "flight_header"
                and b["header"].get("reason") == "regression"
                and isinstance(b["header"].get("verdict"), dict)
                and len(b["steps"]) > 0)
        rec["bundle_roundtrip"] = bundle_ok
        fps = (serving.get("false_positives", 0)
               + training.get("false_positives", 0))
        rec["false_positives"] = fps
        rec["baselines_persisted"] = os.path.isfile(store_path)
        rec["ok"] = bool(
            serving.get("detected")
            and serving.get("cause") == CAUSE_CONTENTION
            and serving.get("detect_windows") is not None
            and serving["detect_windows"] <= 5
            and training.get("detected")
            and training.get("cause") == CAUSE_COMPILE
            and training.get("detect_windows") is not None
            and training["detect_windows"] <= 5
            and fps == 0
            and bundle_ok
            and serving.get("report_has_table")
            and rec["baselines_persisted"])
    finally:
        reset()
        diag.stop_diag_server()
    lines = [
        {"metric": "regress_contention_detect_windows",
         "value": float(rec.get("serving", {}).get("detect_windows")
                        or 99.0), "unit": "windows"},
        {"metric": "regress_compile_detect_windows",
         "value": float(rec.get("training", {}).get("detect_windows")
                        or 99.0), "unit": "windows"},
        {"metric": "regress_false_positives",
         "value": float(rec.get("false_positives") or 0.0),
         "unit": "count"},
        {"metric": "regress_bundle_roundtrip",
         "value": 1.0 if rec.get("bundle_roundtrip") else 0.0,
         "unit": "bool"},
        rec,
    ]
    with open(args.out, "w", encoding="utf-8") as f:
        for obj in lines:
            f.write(json.dumps(obj, sort_keys=True, default=str) + "\n")
    print(json.dumps(rec, indent=2, sort_keys=True, default=str))
    return 0 if rec["ok"] else 1


def main(argv=None) -> int:
    import argparse
    p = argparse.ArgumentParser(
        prog="python -m singa_tpu.regress",
        description="performance regression observatory: --ab runs "
                    "the injected-regression harness (contention + "
                    "compile legs, clean arms gated on zero false "
                    "positives)")
    p.add_argument("--ab", action="store_true")
    p.add_argument("--seed", type=int, default=1234)
    p.add_argument("--warmup", type=int, default=16)
    p.add_argument("--window", type=int, default=4)
    p.add_argument("--clean-windows", type=int, default=3)
    # serving leg
    p.add_argument("--vocab", type=int, default=211)
    p.add_argument("--dim", type=int, default=64)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--slots", type=int, default=2)
    p.add_argument("--prompt-lo", type=int, default=4)
    p.add_argument("--prompt-hi", type=int, default=12)
    p.add_argument("--new-tokens", type=int, default=16)
    p.add_argument("--step-delay", type=float, default=0.05,
                   help="per-decode-sync stall injected at the "
                        "serving.engine_step fault point (inside the "
                        "span the detector watches)")
    p.add_argument("--burst", type=int, default=32,
                   help="requests submitted at the injection edge so "
                        "the admission queue deepens past its "
                        "baseline level (the contention evidence)")
    # training leg
    p.add_argument("--features", type=int, default=512)
    p.add_argument("--hidden", type=int, default=512)
    p.add_argument("--batch-lo", type=int, default=8)
    p.add_argument("--batch-hi", type=int, default=512)
    p.add_argument("--timeout", type=float, default=300.0)
    p.add_argument("--out", default="REGRESS_r01.json")
    args = p.parse_args(argv)
    if args.ab:
        return _ab_main(args)
    p.error("pick a mode: --ab")
    return 2


__all__ = [
    "REGRESS_CAUSES",
    "BaselineStore", "RegressionDetector",
    "install", "get_detector", "uninstall", "reset",
    "fleet_regress_snapshot", "fleet_regress_vote", "fleetz_lines",
    "regress_report", "regress_json",
]

if __name__ == "__main__":
    # run under the CANONICAL module (not the runpy __main__ alias): the
    # CLI installs the module singleton the diag/fleet layers reach via
    # `import singa_tpu.regress`
    from singa_tpu.regress import main as _main
    sys.exit(_main())
