"""Native (C++) runtime components, built on demand with g++.

`lib()` compiles native/recordio.cc into a cached shared object and loads
it via ctypes (this environment has no pybind11; ctypes IS the binding
layer). Falls back to None when no compiler is available — singa_tpu.io
then uses its pure-Python path.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "recordio.cc")
_SO = os.path.join(_DIR, "librecordio.so")

_lock = threading.Lock()
_lib = None
_tried = False


def _compile() -> bool:
    if os.path.exists(_SO) and \
            os.path.getmtime(_SO) >= os.path.getmtime(_SRC):
        return True
    try:
        subprocess.run(
            ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
             _SRC, "-o", _SO + ".tmp"],
            check=True, capture_output=True, timeout=120)
        os.replace(_SO + ".tmp", _SO)
        return True
    except (OSError, subprocess.SubprocessError):
        return False


def lib():
    """The loaded ctypes library, or None if unavailable."""
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        if not _compile():
            return None
        lb = ctypes.CDLL(_SO)
        lb.rio_writer_open.restype = ctypes.c_void_p
        lb.rio_writer_open.argtypes = [ctypes.c_char_p]
        lb.rio_writer_write.restype = ctypes.c_int
        lb.rio_writer_write.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32,
            ctypes.c_char_p, ctypes.c_uint64]
        lb.rio_writer_close.restype = ctypes.c_int
        lb.rio_writer_close.argtypes = [ctypes.c_void_p]
        lb.rio_reader_open.restype = ctypes.c_void_p
        lb.rio_reader_open.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lb.rio_reader_next.restype = ctypes.c_int
        lb.rio_reader_next.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_uint32),
            ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_uint64)]
        lb.rio_reader_close.restype = None
        lb.rio_reader_close.argtypes = [ctypes.c_void_p]
        _lib = lb
        return _lib
