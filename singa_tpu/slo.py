"""Request-level serving observability: timelines, SLOs, burn rates.

PR 11's continuous-batching engine made serving *fast*; its telemetry
stayed aggregate — counters and percentile gauges that cannot answer
"where did request #4812's 900ms TTFT go?" or "are we inside our p99
SLO right now?". This module is the request-level layer over the same
engine, three pieces:

  - **Per-request trace timelines**: every `engine.EngineRequest`
    records phase-stamped lifecycle events from the fixed
    `REQUEST_PHASES` enum (submit -> queue -> admit -> prefill ->
    first_token -> per-sync decode progress with tokens-so-far ->
    terminal), ring-buffered per engine (`ServingEngine.timelines()`,
    a LOCKED copy — diag handler threads read while the decode thread
    appends). `engine_trace_events()` exports them as Perfetto/Chrome
    Trace Event JSON: one track per decode slot plus a queue track,
    with **flow events linking each request's decode span to the
    engine decode-step slices it rode** (the sync ring records each
    sync's t0/duration/thread, so the flow binds inside the real
    `serving.engine_step` slice). The same builder merges per-worker
    timelines into `fleet.export_trace` via the existing clock
    handshake, so a multi-replica trace shows requests flowing through
    workers.

  - **SLO tracker**: `SLOConfig` declares targets (p99 TTFT, p99
    request latency, availability = non-timeout/evicted fraction,
    min tokens/sec for completed requests); `SLOTracker` subscribes to
    the engine's terminal-request stream
    (`engine.add_request_listener`), evaluates attainment over sliding
    windows and computes the multi-window **error-budget burn rate**
    (fast 5m / slow 1h style, scaled for tests): with a p99 target the
    error budget is 1%, and burn = observed-violation-fraction /
    budget — burn 1.0 spends the budget exactly at the window's pace,
    burn >> 1 exhausts it early. A breach (both windows over
    `burn_threshold` for `sustain` consecutive evaluations) feeds
    `HealthMonitor.note_external(KIND_SLO)`, so /healthz reflects
    serving health the same way it reflects stragglers and leaks.
    Exports `singa_slo_*` metrics.

  - **The serving surfaces**: `/slo` (diag server) renders the config,
    per-objective attainment and burn rates, and the recent violating
    request ids WITH their timelines (`?json=1` for the structured
    form); `fleet_serve_snapshot()` rides every fleet shard as a
    `fleet_serve` line so `/fleetz` grows the per-replica serving
    columns (RPS, queue depth, occupancy, page utilization, TTFT
    percentiles, kv-cache bytes from the memory ledger, SLO
    attainment) the ROADMAP's serving control plane needs to route
    and autoscale against.

Clocks: timeline events are stamped with `time.perf_counter()` — the
same clock the observe span ring and the fleet (epoch, perf) handshake
use, so merged traces align without a second handshake. The tracker's
sliding windows run on the same stamps.

CLI: `python -m singa_tpu.slo --ab --out SLO_r01.json` runs the
acceptance A/B — a clean Poisson serving run (100% attainment) vs one
with a FaultPlan-injected delay on `serving.engine_step` (TTFT
degradation), asserting the burn-rate verdict fires within K
evaluation windows and the merged trace flow-links a chosen request to
the decode-step slices it rode.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque

from . import observe

#: every lifecycle phase a request's timeline can record (the `phase=`
#: label on singa_slo_phase_seconds is proven against this tuple by
#: tools/check_metrics_names.py rule 5).
REQUEST_PHASES = ("submit", "queue", "admit", "prefill", "first_token",
                  "decode", "terminal")
PHASE_SUBMIT = "submit"
PHASE_QUEUE = "queue"
PHASE_ADMIT = "admit"
PHASE_PREFILL = "prefill"
PHASE_FIRST_TOKEN = "first_token"
PHASE_DECODE = "decode"
PHASE_TERMINAL = "terminal"

#: every declarable serving objective (the `objective=` label on the
#: singa_slo_* metrics is proven against this tuple by rule 5).
SLO_OBJECTIVES = ("ttft_p99", "latency_p99", "availability",
                  "tokens_per_sec")

#: every latency-attribution bucket a terminal request's wall time can
#: decompose into (the `attr=` label on singa_tail_seconds_total is
#: proven against this tuple by rule 5). The decomposition is pure
#: math over the phase-stamped timelines and MUST sum to the request's
#: total latency — the same wall-sum discipline as the goodput
#: buckets, test-enforced.
LATENCY_ATTR = ("router_queue", "probe", "dispatch_retry",
                "replica_queue", "prefill", "decode", "decode_stall",
                "failover_replay", "other")
ATTR_ROUTER_QUEUE = "router_queue"
ATTR_PROBE = "probe"
ATTR_DISPATCH_RETRY = "dispatch_retry"
ATTR_REPLICA_QUEUE = "replica_queue"
ATTR_PREFILL = "prefill"
ATTR_DECODE = "decode"
ATTR_DECODE_STALL = "decode_stall"
ATTR_FAILOVER_REPLAY = "failover_replay"
ATTR_OTHER = "other"


_metrics_cache: "dict | None" = None


def _metrics():
    # observe.counter/gauge/histogram spelled out so the static lint
    # sees every registration; objective=/phase= label values are
    # members of SLO_OBJECTIVES / REQUEST_PHASES (enum-guarded at the
    # record sites). Memoized behind one sentinel lookup (the engine's
    # pattern): this runs per terminal request and per evaluation on
    # the serving path, and 9 locked registry lookups per call is
    # repeated work — revalidated so a conftest registry reset rebuilds
    # instead of feeding orphaned metric objects.
    global _metrics_cache
    c = _metrics_cache
    if c is not None and observe.get_registry().get(
            "singa_slo_attainment_pct") is c["attainment"]:
        return c
    _metrics_cache = c = {
        "attainment": observe.gauge(
            "singa_slo_attainment_pct",
            "per-objective SLO attainment over the sliding window "
            "(percent of applicable requests meeting the target)"),
        "burn_fast": observe.gauge(
            "singa_slo_burn_rate_fast",
            "error-budget burn rate over the FAST window "
            "(violation fraction / error budget)"),
        "burn_slow": observe.gauge(
            "singa_slo_burn_rate_slow",
            "error-budget burn rate over the SLOW window"),
        "budget": observe.gauge(
            "singa_slo_error_budget_remaining",
            "1 - slow-window burn rate: the share of the error budget "
            "left at the current violation rate"),
        "window_requests": observe.gauge(
            "singa_slo_window_requests",
            "terminal requests inside the attainment window"),
        "evals": observe.counter(
            "singa_slo_evaluations_total",
            "SLO tracker evaluation passes"),
        "violations": observe.counter(
            "singa_slo_violations_total",
            "requests that violated an objective, by objective"),
        "breaches": observe.counter(
            "singa_slo_breach_total",
            "sustained burn-rate breach verdicts, by objective"),
        "phase": observe.histogram(
            "singa_slo_phase_seconds",
            "wall seconds a request spent in each lifecycle phase"),
        "tail": observe.counter(
            "singa_tail_seconds_total",
            "terminal-request wall seconds attributed to each "
            "latency bucket (LATENCY_ATTR decomposition)"),
    }
    return c


# ---- configuration ---------------------------------------------------------

class SLOConfig:
    """Declared serving objectives. An objective is ENABLED iff its
    target is not None:

      ttft_p99_s          p99 submit-to-first-token (percentile target:
                          `percentile` of requests must meet it)
      latency_p99_s       p99 submit-to-terminal latency, judged on
                          completed requests
      availability        fraction of requests that must finish
                          neither "timeout" nor "evicted"
      min_tokens_per_sec  per-request generation-rate floor, judged on
                          completed requests

    Window geometry: `window_s` is the attainment window the gauges
    report over; `fast_window_s` / `slow_window_s` are the two
    burn-rate windows (the classic 5m/1h pair, scaled down for tests);
    a breach needs BOTH over `burn_threshold` for `sustain`
    consecutive evaluations, at least `min_requests` requests in the
    slow window, and `eval_interval_s` throttles request-driven
    evaluation."""

    def __init__(self, ttft_p99_s=None, latency_p99_s=None,
                 availability=None, min_tokens_per_sec=None,
                 percentile=0.99, window_s=60.0, fast_window_s=5.0,
                 slow_window_s=30.0, burn_threshold=2.0, sustain=2,
                 min_requests=5, eval_interval_s=0.5):
        self.ttft_p99_s = ttft_p99_s
        self.latency_p99_s = latency_p99_s
        self.availability = availability
        self.min_tokens_per_sec = min_tokens_per_sec
        self.percentile = float(percentile)
        self.window_s = float(window_s)
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.burn_threshold = float(burn_threshold)
        self.sustain = int(sustain)
        self.min_requests = int(min_requests)
        self.eval_interval_s = float(eval_interval_s)

    def enabled(self):
        """The objectives this config declares, in enum order."""
        on = []
        for obj in SLO_OBJECTIVES:
            if self._target_value(obj) is not None:
                on.append(obj)
        return on

    def _target_value(self, objective):
        return {"ttft_p99": self.ttft_p99_s,
                "latency_p99": self.latency_p99_s,
                "availability": self.availability,
                "tokens_per_sec": self.min_tokens_per_sec}[objective]

    def target_fraction(self, objective) -> float:
        """The good-fraction the objective demands: `percentile` for
        the percentile/rate objectives, the availability itself for
        availability. Error budget = 1 - target_fraction."""
        if objective == "availability":
            return float(self.availability)
        return self.percentile

    def snapshot(self) -> dict:
        return {
            "ttft_p99_s": self.ttft_p99_s,
            "latency_p99_s": self.latency_p99_s,
            "availability": self.availability,
            "min_tokens_per_sec": self.min_tokens_per_sec,
            "percentile": self.percentile,
            "window_s": self.window_s,
            "fast_window_s": self.fast_window_s,
            "slow_window_s": self.slow_window_s,
            "burn_threshold": self.burn_threshold,
            "sustain": self.sustain,
            "min_requests": self.min_requests,
        }


# ---- the pure math ---------------------------------------------------------
# Free functions over plain record dicts, so bench_decode's static arm
# (which has no engine, only measured latencies) and the tests' synthetic
# violation sequences evaluate with EXACTLY the tracker's arithmetic.

def objective_good(objective, rec, cfg) -> "bool | None":
    """Whether one terminal-request record meets `objective` (None =
    the objective does not apply to this record). Records are the
    tracker's shape: {"outcome", "ttft_s", "total_s",
    "tokens_per_sec"}. Rejected requests are deliberate admission-
    control shed: they are excluded from the latency-shaped objectives
    and count as AVAILABLE (the declared availability objective is the
    non-timeout/evicted fraction)."""
    assert objective in SLO_OBJECTIVES, objective
    outcome = rec.get("outcome")
    if objective == "availability":
        return outcome not in ("timeout", "evicted")
    if outcome == "rejected":
        return None
    if objective == "ttft_p99":
        ttft = rec.get("ttft_s")
        if ttft is None:
            # a queue-expired timeout never reached a first token —
            # that IS a TTFT violation; a path that simply doesn't
            # measure TTFT (the fused beam program has no prefill
            # seam) is not applicable, not failing
            return False if outcome == "timeout" else None
        return float(ttft) <= float(cfg.ttft_p99_s)
    if outcome != "completed":
        return None  # latency/rate are judged on successes only
    if objective == "latency_p99":
        total = rec.get("total_s")
        if total is None:
            return None  # missing sample = N/A, like ttft/rate
        return float(total) <= float(cfg.latency_p99_s)
    rate = rec.get("tokens_per_sec")
    if rate is None:
        return None
    return float(rate) >= float(cfg.min_tokens_per_sec)


def attainment(records, cfg, now=None, window_s=None) -> dict:
    """{objective: {"attainment", "good", "total"}} over the records
    inside the window (all records when `now` is None). `attainment`
    is None when no record was applicable."""
    if now is not None:
        w = cfg.window_s if window_s is None else window_s
        records = [r for r in records if now - r["ts"] <= w]
    out = {}
    for obj in cfg.enabled():
        good = total = 0
        for r in records:
            g = objective_good(obj, r, cfg)
            if g is None:
                continue
            total += 1
            good += 1 if g else 0
        out[obj] = {"good": good, "total": total,
                    "attainment": (good / total) if total else None}
    return out


def burn_rate(att: "float | None", target: float) -> "float | None":
    """Error-budget burn: observed violation fraction / budget. 1.0
    spends the budget exactly at the window's pace; None when the
    window held no applicable request. The budget is floored so a
    target of 1.0 (zero budget) yields a huge-but-finite burn instead
    of dividing by zero."""
    if att is None:
        return None
    budget = max(1.0 - float(target), 1e-6)
    return (1.0 - float(att)) / budget


# ---- the tracker -----------------------------------------------------------

def request_latency_sample(req, timeline: dict) -> "dict | None":
    """Reduce one terminal (req, timeline) listener callback to the
    latency sample the regression detector feeds on: {"ttft_s",
    "itl_s", "tokens"}. None for anything that should not calibrate or
    convict a latency baseline — synthetic audit probes (same door as
    note_timeline), non-completed outcomes (an eviction's short total
    is not a latency), and requests that never produced a first token.
    itl_s is the mean inter-token latency (decode span over tokens
    after the first); None when only one token was produced."""
    if not timeline or timeline.get("synthetic"):
        return None
    if timeline.get("outcome") != "completed":
        return None
    ttft = timeline.get("ttft_s")
    if ttft is None:
        return None
    tokens = int(timeline.get("new_tokens") or 0)
    itl = None
    total = timeline.get("total_s")
    if total is not None and tokens > 1:
        itl = max(0.0, (float(total) - float(ttft)) / (tokens - 1))
    return {"ttft_s": float(ttft), "itl_s": itl, "tokens": tokens}


class SLOTracker:
    """Evaluates an `SLOConfig` over the engine's terminal-request
    stream. `install()` subscribes it to `engine.add_request_listener`
    (and registers it module-wide so /slo, the fleet shard writer and
    the conftest teardown find it); every terminal request lands in a
    bounded record window, throttle-evaluated. `policy` resolves the
    breach action like the fleet aggregator's: None inherits the
    active HealthMonitor's ("halt" stays halt, anything else warns)."""

    def __init__(self, config: "SLOConfig | None" = None, policy=None,
                 capacity=4096, clock=time.perf_counter):
        from . import health
        if policy is not None and policy not in ("warn", "halt"):
            raise ValueError(
                f"policy {policy!r} not in ('warn', 'halt')")
        self.config = config or SLOConfig()
        self.policy = policy
        self.clock = clock
        self._lock = threading.Lock()
        self._records: "deque[dict]" = deque(maxlen=int(capacity))
        self._violations: "deque[dict]" = deque(maxlen=32)
        self._over = {}        # objective -> consecutive burning evals
        self._breached = set()  # objectives inside a breach episode
        self._last_eval = 0.0
        self._last_verdict = None
        self._evals = 0
        self._health = health

    # -- feeding -----------------------------------------------------------
    def _on_request(self, req, timeline):
        """engine request listener: (EngineRequest, timeline dict)."""
        self.note_timeline(timeline)

    def note_timeline(self, timeline: dict):
        """Feed one finished request timeline (the engine's ring
        shape). Derives the tracker record, books per-phase durations,
        tracks violations for the /slo display, and throttle-runs an
        evaluation pass. Synthetic (audit canary/replay) timelines are
        dropped at the door: a probe storm must never move SLO
        attainment or burn the error budget — correctness probing is
        the audit module's verdict, not demand-facing load."""
        if timeline.get("synthetic"):
            return
        events = timeline.get("events") or []
        ts = events[-1][1] if events else self.clock()
        rec = {
            "ts": float(ts),
            "id": timeline.get("id"),
            "outcome": timeline.get("outcome"),
            "ttft_s": timeline.get("ttft_s"),
            "total_s": timeline.get("total_s"),
            "tokens_per_sec": timeline.get("tokens_per_sec"),
        }
        self.note_record(rec, timeline=timeline)

    def note_record(self, rec: dict, timeline: "dict | None" = None):
        """Feed one plain terminal record ({"ts", "outcome", "ttft_s",
        "total_s", "tokens_per_sec"}) — the no-engine path tests and
        bench arms use."""
        cfg = self.config
        violated = [obj for obj in cfg.enabled()
                    if objective_good(obj, rec, cfg) is False]
        with self._lock:
            self._records.append(dict(rec))
            if violated:
                self._violations.append({
                    "id": rec.get("id"), "ts": rec.get("ts"),
                    "outcome": rec.get("outcome"),
                    "objectives": violated,
                    "ttft_s": rec.get("ttft_s"),
                    "total_s": rec.get("total_s"),
                    "timeline": timeline,
                    # where the violating request's wall time WENT —
                    # the /slo display answers "which bucket" without
                    # a trip to /tailz
                    "attr": attribute_timeline(timeline)
                    if timeline is not None else None,
                })
        if observe.is_enabled():
            m = _metrics()
            for obj in violated:
                assert obj in SLO_OBJECTIVES
                m["violations"].inc(objective=obj)
            if timeline is not None:
                for phase, dur in phase_durations(timeline):
                    if phase in REQUEST_PHASES:
                        m["phase"].observe(dur, phase=phase)
        self.maybe_evaluate()

    # -- evaluation ----------------------------------------------------------
    def maybe_evaluate(self):
        now = self.clock()
        with self._lock:
            # claim the evaluation slot UNDER the lock: the engine
            # listener, diag handlers and the fleet writer all arrive
            # here concurrently, and an unlocked check-then-act would
            # let two of them evaluate inside one interval — double-
            # advancing the sustain counter on poll timing, which the
            # state machine's contract forbids
            if now - self._last_eval < self.config.eval_interval_s:
                return
            self._last_eval = now
        self.evaluate(now=now)

    def evaluate(self, now=None) -> dict:
        """One evaluation pass: window attainment, fast/slow burn per
        objective, sustained-breach bookkeeping (feeding
        `HealthMonitor.note_external(KIND_SLO)` once per episode), and
        the singa_slo_* gauge exports. Returns the verdict dict. The
        breach state machine advances UNDER the tracker lock — this is
        reachable concurrently from the engine's terminal-request
        listener, diag handler threads and the fleet shard writer, and
        a lost sustain increment (or a doubled episode fire) must not
        depend on poll timing. objective_good runs ONCE per (record,
        objective); the three windows tally from the same pass."""
        cfg = self.config
        now = self.clock() if now is None else now
        objectives = {}
        fired = []
        with self._lock:
            records = list(self._records)
            ages = [now - r["ts"] for r in records]
            n_window = sum(1 for a in ages if a <= cfg.window_s)
            for obj in cfg.enabled():
                target = cfg.target_fraction(obj)
                gw = tw = gf = tf = gs = ts_ = 0
                for r, age in zip(records, ages):
                    if age > cfg.window_s \
                            and age > cfg.fast_window_s \
                            and age > cfg.slow_window_s:
                        continue
                    g = objective_good(obj, r, cfg)
                    if g is None:
                        continue
                    if age <= cfg.window_s:
                        tw += 1
                        gw += g
                    if age <= cfg.fast_window_s:
                        tf += 1
                        gf += g
                    if age <= cfg.slow_window_s:
                        ts_ += 1
                        gs += g
                att_w = (gw / tw) if tw else None
                fast = burn_rate((gf / tf) if tf else None, target)
                slow = burn_rate((gs / ts_) if ts_ else None, target)
                burning = (
                    fast is not None and slow is not None
                    and fast > cfg.burn_threshold
                    and slow > cfg.burn_threshold
                    and ts_ >= cfg.min_requests)
                self._over[obj] = self._over.get(obj, 0) + 1 \
                    if burning else 0
                breach = False
                if self._over[obj] >= cfg.sustain:
                    breach = True
                    if obj not in self._breached:
                        self._breached.add(obj)
                        fired.append((obj, fast, slow, att_w))
                elif not burning:
                    self._breached.discard(obj)  # episode over: re-arm
                objectives[obj] = {
                    "target": cfg._target_value(obj),
                    "target_fraction": target,
                    "attainment": att_w,
                    "good": gw,
                    "total": tw,
                    "burn_fast": fast,
                    "burn_slow": slow,
                    "burning": burning,
                    "breach": breach,
                }
            self._evals += 1
            self._last_eval = now
            verdict = {
                "ts": round(now, 6),
                "window_requests": n_window,
                "objectives": objectives,
                "breaching": sorted(self._breached),
                "evaluations": self._evals,
            }
            self._last_verdict = verdict
        if observe.is_enabled():
            m = _metrics()
            m["evals"].inc()
            m["window_requests"].set(float(n_window))
            for obj in SLO_OBJECTIVES:
                o = objectives.get(obj)
                if o is None:
                    continue
                if o["attainment"] is not None:
                    m["attainment"].set(100.0 * o["attainment"],
                                        objective=obj)
                if o["burn_fast"] is not None:
                    m["burn_fast"].set(o["burn_fast"], objective=obj)
                if o["burn_slow"] is not None:
                    m["burn_slow"].set(o["burn_slow"], objective=obj)
                    m["budget"].set(1.0 - o["burn_slow"],
                                    objective=obj)
        self._fire(fired)
        return verdict

    def _resolved_policy(self) -> str:
        if self.policy is not None:
            return self.policy
        mon = self._health.active_monitor()
        if mon is not None and mon.policy == "halt":
            return "halt"
        return "warn"

    def _fire(self, fired):
        """New sustained-breach verdicts: counted, event-logged, fed to
        the active HealthMonitor with the RESOLVED action (the tracker's
        policy may override the monitor's — /healthz must not disagree
        with /slo about whether a halt happened)."""
        if not fired:
            return
        policy = self._resolved_policy()
        mon = self._health.active_monitor()
        for obj, fast, slow, att in fired:
            assert obj in SLO_OBJECTIVES
            detail = {"objective": obj,
                      "burn_fast": round(fast, 3)
                      if fast is not None else None,
                      "burn_slow": round(slow, 3)
                      if slow is not None else None,
                      "attainment": round(att, 4)
                      if att is not None else None}
            if observe.is_enabled():
                # metric/event plumbing honors the master switch like
                # every other record site; the monitor note below does
                # NOT — the breach verdict is health state, not
                # telemetry
                _metrics()["breaches"].inc(objective=obj)
                observe.get_registry().emit(
                    {"kind": "slo", "event": "burn_breach", **detail,
                     "policy": policy})
            if mon is not None:
                try:
                    mon.note_external(
                        self._health.KIND_SLO, detail=detail,
                        action="halt" if policy == "halt" else "warn")
                except Exception:
                    pass  # the monitor must not break the tracker

    # -- reading -------------------------------------------------------------
    def last_verdict(self) -> "dict | None":
        return self._last_verdict

    def current_verdict(self) -> dict:
        """The read-only surfaces' verdict (/slo, /statusz, fleet
        shard publishes): evaluates only when the eval cadence allows,
        so poll frequency cannot advance the 'sustain consecutive
        evaluations' breach state machine faster than the configured
        interval — a scrape must observe, not convict."""
        self.maybe_evaluate()
        v = self._last_verdict
        return v if v is not None else self.evaluate()

    def breaching(self) -> list:
        with self._lock:
            return sorted(self._breached)

    def violations(self) -> list:
        """Locked copy of the recent violating requests (newest last),
        each with the objectives it violated and — when it came off an
        engine — its full timeline."""
        with self._lock:
            return list(self._violations)

    def window_records(self, now=None, window_s=None) -> list:
        cfg = self.config
        now = self.clock() if now is None else now
        w = cfg.window_s if window_s is None else window_s
        with self._lock:
            return [dict(r) for r in self._records
                    if now - r["ts"] <= w]

    # -- lifecycle -----------------------------------------------------------
    def install(self) -> "SLOTracker":
        """Register module-wide and subscribe to the engine's terminal
        stream. A second install replaces the previous tracker (its
        listener detached)."""
        return install(self)

    def uninstall(self):
        if get_tracker() is self:
            uninstall()


# ---- module singleton (the conftest teardown contract) ---------------------

_tracker: "SLOTracker | None" = None
_lock = threading.Lock()


def install(tracker: "SLOTracker") -> "SLOTracker":
    """Install `tracker` as the process SLO tracker: /slo, the fleet
    shard writer and the serving wiring all answer from it. Replaces
    (and detaches) any previous tracker."""
    global _tracker
    from . import engine
    with _lock:
        old = _tracker
        if old is not None:
            engine.remove_request_listener(old._on_request)
        _tracker = tracker
        engine.add_request_listener(tracker._on_request)
    return tracker


def uninstall():
    """Remove the installed tracker and detach its engine listener."""
    global _tracker
    from . import engine
    with _lock:
        t = _tracker
        _tracker = None
        if t is not None:
            engine.remove_request_listener(t._on_request)


def get_tracker() -> "SLOTracker | None":
    return _tracker


def reset():
    """Full teardown (the conftest contract): the tracker uninstalled
    and its engine request listener detached — no evaluation state,
    listeners or records leak between tests. The tail-attribution
    collector and its store reset on the same contract."""
    uninstall()
    tail_reset()


def note_decode(kind: str, seconds: float, new_tokens: int,
                ttft: "float | None" = None, batch: int = 1):
    """serving.py wiring: one STATIC-batch decode call fed to the
    installed tracker, so a deployment still on the dense path gets
    /slo attainment (latency + tokens/sec; TTFT when the greedy path
    fenced one) without the engine. The call carries `batch` requests:
    each is recorded as its OWN sample with its PER-REQUEST rate
    (new_tokens/batch over the call wall) — min_tokens_per_sec is a
    per-request floor everywhere else, and a batch must not weigh as
    one request against the engine's per-request stream. No-op without
    a tracker."""
    t = get_tracker()
    if t is None:
        return
    batch = max(1, int(batch))
    rec = {
        "ts": t.clock(), "id": None, "outcome": "completed",
        "kind": kind, "ttft_s": ttft, "total_s": float(seconds),
        "tokens_per_sec": (new_tokens / batch / seconds)
        if seconds > 0 else None,
    }
    # the static path has no phase-stamped timeline, but the call wall
    # still decomposes: the fenced TTFT is the prefill share, the rest
    # is decode — so a dense deployment's /tailz is populated too
    attr = None
    if seconds > 0:
        if ttft is not None and 0.0 < float(ttft) <= float(seconds):
            attr = {ATTR_PREFILL: float(ttft),
                    ATTR_DECODE: float(seconds) - float(ttft)}
        else:
            attr = {ATTR_DECODE: float(seconds)}
    for _ in range(batch):
        t.note_record(dict(rec))
        if attr is not None:
            note_attribution({"id": None, "outcome": "completed",
                              "trace": None,
                              "total_s": float(seconds),
                              "attr": dict(attr)})


# ---- per-phase durations ---------------------------------------------------

def phase_durations(timeline: dict):
    """[(phase, seconds)] from one timeline's phase-stamped events:
    each interval between consecutive events is attributed to the
    EARLIER event's phase (repeated per-sync `decode` marks all book
    under decode). The terminal event closes the last interval and has
    no duration of its own."""
    events = timeline.get("events") or []
    out = []
    for (phase, t, _info), (_p2, t2, _i2) in zip(events, events[1:]):
        out.append((phase, max(0.0, float(t2) - float(t))))
    return out


# ---- tail-latency attribution ----------------------------------------------
# Pure math over the phase-stamped timelines: every terminal request's
# wall time decomposes into the closed LATENCY_ATTR buckets, and the
# buckets MUST sum to the request's total latency — the same wall-sum
# discipline as the goodput buckets, test-enforced. Two decomposers:
# one for an ENGINE timeline (inside a replica), one for a ROUTER
# request (across dispatch/failover hops, adopting the winning
# replica's engine-side buckets for the final hop).

def attribute_timeline(timeline: dict) -> dict:
    """{bucket: seconds} for one engine timeline, summing exactly to
    last-event - first-event. submit/queue intervals book as
    `replica_queue`, admit/prefill as `prefill`; the inter-sync decode
    gaps split into steady `decode` plus `decode_stall` — any gap's
    excess beyond 2x the median gap (an injected delay, a preempting
    tenant, a straggling sync) with >= 3 gaps to estimate the median
    from. Anything unclassifiable books as `other`. Empty dict for a
    timeline with fewer than two events (nothing to attribute)."""
    events = timeline.get("events") or []
    out = {}
    gaps = []
    for (phase, t, _i), (_p2, t2, _i2) in zip(events, events[1:]):
        d = max(0.0, float(t2) - float(t))
        if phase in (PHASE_SUBMIT, PHASE_QUEUE):
            k = ATTR_REPLICA_QUEUE
        elif phase in (PHASE_ADMIT, PHASE_PREFILL):
            k = ATTR_PREFILL
        elif phase in (PHASE_FIRST_TOKEN, PHASE_DECODE):
            gaps.append(d)
            continue
        else:
            k = ATTR_OTHER
        out[k] = out.get(k, 0.0) + d
    if gaps:
        total = sum(gaps)
        stall = 0.0
        if len(gaps) >= 3:
            med = sorted(gaps)[len(gaps) // 2]
            stall = min(sum(max(0.0, g - 2.0 * med) for g in gaps),
                        total)
        out[ATTR_DECODE] = total - stall
        if stall > 0.0:
            out[ATTR_DECODE_STALL] = stall
    return {k: round(v, 7) for k, v in out.items()}


def attribute_route(submitted, finished, events,
                    replica_attr: "dict | None" = None) -> dict:
    """{bucket: seconds} for one ROUTER request's wall time (submit ->
    terminal), from its mark() events, summing exactly to finished -
    submitted. `router_queue` runs up to the first dispatch; each hop
    that failed over books its dead-replica probe under `probe` and
    the rest under `failover_replay` (the replica had ACCEPTED the
    work — the retry replays tokens already generated) or
    `dispatch_retry` (it never started; includes the backoff); the
    final hop adopts the winning replica's own engine-side buckets
    (`replica_attr`) clipped to the hop wall, any remainder —
    transport, HTTP framing, poll granularity — under `other`."""
    out = {}
    dispatches = [(float(t), i or {}) for (n, t, i) in events or ()
                  if n == "dispatch"]
    failovers = [(float(t), i or {}) for (n, t, i) in events or ()
                 if n == "failover"]

    def add(k, v):
        if v > 0.0:
            out[k] = out.get(k, 0.0) + v

    if not dispatches:
        # never dispatched: shed / drained / queue-expired in the
        # router — the whole wall is router queue time
        add(ATTR_ROUTER_QUEUE,
            max(0.0, float(finished) - float(submitted)))
        return {k: round(v, 7) for k, v in out.items()}
    add(ATTR_ROUTER_QUEUE,
        max(0.0, dispatches[0][0] - float(submitted)))
    for k, (t, _info) in enumerate(dispatches):
        end = dispatches[k + 1][0] if k + 1 < len(dispatches) \
            else float(finished)
        wall = max(0.0, end - t)
        if k < len(failovers):
            f_info = failovers[k][1]
            probe = min(max(0.0, float(f_info.get("probe_s") or 0.0)),
                        wall)
            add(ATTR_PROBE, probe)
            add(ATTR_FAILOVER_REPLAY if f_info.get("pending")
                else ATTR_DISPATCH_RETRY, wall - probe)
        elif replica_attr:
            known = 0.0
            for rk in LATENCY_ATTR:
                rv = min(max(0.0, float(replica_attr.get(rk) or 0.0)),
                         wall - known)
                add(rk, rv)
                known += rv
            add(ATTR_OTHER, wall - known)
        else:
            add(ATTR_OTHER, wall)
    return {k: round(v, 7) for k, v in out.items()}


# -- the tail store (what /tailz aggregates) ---------------------------------

_tail_lock = threading.Lock()
_tail: "deque[dict]" = deque(maxlen=4096)
_tail_collector: "TailCollector | None" = None


def note_attribution(rec: dict):
    """Feed one terminal request's decomposition into the tail store
    ({"id", "outcome", "trace", "total_s", "attr"}) and the
    singa_tail_seconds_total counter. Buckets outside the enum fold
    into `other` — the counter's label set must stay closed."""
    attr = {}
    for k, v in (rec.get("attr") or {}).items():
        k = k if k in LATENCY_ATTR else ATTR_OTHER
        attr[k] = attr.get(k, 0.0) + float(v)
    rec = dict(rec)
    rec["attr"] = attr
    with _tail_lock:
        _tail.append(rec)
    if observe.is_enabled():
        m = _metrics()
        for k, v in attr.items():
            assert k in LATENCY_ATTR, k
            if v > 0.0:
                m["tail"].inc(float(v), attr=k)


def tail_records() -> list:
    """Locked copy of the attributed-request records (newest last)."""
    with _tail_lock:
        return [dict(r) for r in _tail]


def tail_summary() -> dict:
    """The aggregate /tailz view: request count, total-latency
    percentiles, and per-bucket totals with each bucket's p99
    CONTRIBUTION — the p99 of that bucket's per-request seconds
    (zeros included, so a bucket touching one request in a thousand
    ranks by what it does to the fleet tail, not to its own). `top`
    names the bucket with the largest p99 contribution: the one-word
    answer to "where did the tail go"."""
    from . import engine as engine_mod
    recs = tail_records()
    totals = [float(r.get("total_s") or 0.0) for r in recs]
    wall = sum(totals)
    buckets = {}
    for k in LATENCY_ATTR:
        vals = [float((r.get("attr") or {}).get(k) or 0.0)
                for r in recs]
        nz = [v for v in vals if v > 0.0]
        if not nz:
            continue
        buckets[k] = {
            "sum_s": round(sum(nz), 6),
            "share": round(sum(nz) / wall, 4) if wall > 0 else None,
            "p99_s": engine_mod.pctile(vals, 0.99),
            "requests": len(nz),
        }
    top = max(buckets, key=lambda k: buckets[k]["p99_s"] or 0.0) \
        if buckets else None
    return {"requests": len(recs),
            "total_p50_s": engine_mod.pctile(totals, 0.5),
            "total_p99_s": engine_mod.pctile(totals, 0.99),
            "buckets": buckets,
            "top": top}


def tail_report() -> str:
    """The /tailz text block: per-bucket p99 contribution ranking."""
    lines = ["== tailz =="]
    s = tail_summary()
    if not s["requests"]:
        lines.append("no attributed requests yet (terminal requests "
                     "decompose into LATENCY_ATTR buckets here)")
        return "\n".join(lines)
    lines.append(
        f"requests: {s['requests']}   "
        f"total p50 {s['total_p50_s']:.4f}s "
        f"p99 {s['total_p99_s']:.4f}s   "
        f"top p99 contributor: {s['top']}")
    ranked = sorted(s["buckets"].items(),
                    key=lambda kv: kv[1]["p99_s"] or 0.0, reverse=True)
    for k, b in ranked:
        share = f"{100.0 * b['share']:.1f}%" \
            if b["share"] is not None else "-"
        lines.append(
            f"  {k:<16} p99 {b['p99_s']:.4f}s  sum {b['sum_s']:.3f}s "
            f"({share} of wall)  {b['requests']} req")
    return "\n".join(lines)


def tail_json() -> dict:
    """The /tailz?json=1 body: summary + a bounded record tail."""
    s = tail_summary()
    return {"installed": s["requests"] > 0 or get_tail() is not None,
            "summary": s, "records": tail_records()[-64:]}


class TailCollector:
    """Engine request listener feeding the tail store: every terminal
    request's timeline decomposes through `attribute_timeline`.
    Installed NEXT TO (not instead of) the SLOTracker — one listener
    judges objectives, the other attributes the wall time."""

    def _on_request(self, req, timeline):
        attr = attribute_timeline(timeline)
        if not attr:
            return
        total = timeline.get("total_s")
        note_attribution({
            "id": timeline.get("id"),
            "outcome": timeline.get("outcome"),
            "trace": timeline.get("trace"),
            "total_s": total if total is not None
            else round(sum(attr.values()), 7),
            "attr": attr,
        })


def install_tail(collector: "TailCollector | None" = None) \
        -> "TailCollector":
    """Install (or replace) the process tail collector and subscribe
    it to the engine's terminal-request stream."""
    global _tail_collector
    from . import engine
    c = collector or TailCollector()
    with _lock:
        old = _tail_collector
        if old is not None:
            engine.remove_request_listener(old._on_request)
        _tail_collector = c
        engine.add_request_listener(c._on_request)
    return c


def get_tail() -> "TailCollector | None":
    return _tail_collector


def tail_reset():
    """Detach the tail collector's engine listener and clear the
    store (the conftest teardown contract, like the tracker's)."""
    global _tail_collector
    from . import engine
    with _lock:
        c = _tail_collector
        _tail_collector = None
        if c is not None:
            engine.remove_request_listener(c._on_request)
    with _tail_lock:
        _tail.clear()


# ---- trace export ----------------------------------------------------------

#: synthetic track (tid) layout for request slices — far above real OS
#: thread idents stay impossible, so the request tracks are simply
#: distinct, stable and sorted together in Perfetto
QUEUE_TID = 900_000
SLOT_TID_BASE = 900_100

_FLOW_CAT = "req_flow"
#: the CROSS-PROCESS flow category: one flow per router-minted trace
#: id, stepping router queue -> each dispatch hop -> every replica the
#: request touched. Unlike `req_flow` (pid-scoped by construction),
#: linking ACROSS pids is the point — the id is the fleet-unique trace
#: string itself.
TRACE_CTX_CAT = "trace_ctx"


def request_trace_events(timelines, syncs, pid, offset=0.0,
                         emit_sync_slices=True) -> list:
    """Trace Event Format slices for finished request timelines plus
    the engine decode-step slices they rode, with flow events linking
    each request's decode span to those slices. `offset` maps the
    perf_counter stamps onto a shared wall clock (a fleet worker's
    clock-handshake offset; 0.0 for a local export).

    Tracks: one "serve queue" track (queued spans), one "serve slot N"
    track per decode slot (prefill + decode spans), and the
    `serving.engine_step` slices on the decode thread's own tid — the
    same tid the observe span ring publishes. Pass
    `emit_sync_slices=False` when the caller's trace already carries
    the engine_step slices from the span ring (the fleet merge does):
    the sync intervals COVER the span slices on the same tid, so the
    flow events bind inside the real ones and a duplicate overlay
    would only clutter the track."""
    def us(t):
        return round((float(t) + offset) * 1e6, 3)

    events = []
    sync_by_id = {}
    for s in syncs or ():
        sync_by_id[s["sync"]] = s
        if not emit_sync_slices:
            continue
        events.append({
            "name": "serving.engine_step", "cat": "serve", "ph": "X",
            "ts": us(s["t0"]), "dur": round(float(s["dur"]) * 1e6, 3),
            "pid": pid, "tid": int(s.get("tid") or 0),
            "args": {"sync": s["sync"], "slots": s.get("slots"),
                     "steps": s.get("steps"),
                     "tokens": s.get("tokens")},
        })
    for tl in timelines or ():
        rid = tl.get("id")
        evs = tl.get("events") or []
        stamps = {}
        for phase, t, _info in evs:
            stamps.setdefault(phase, float(t))
        t_submit = stamps.get(PHASE_SUBMIT) or stamps.get(PHASE_QUEUE)
        if t_submit is None:
            continue
        t_end = stamps.get(PHASE_TERMINAL)
        in_flight = t_end is None
        if in_flight:
            # an IN-FLIGHT timeline (the replica died mid-request, or
            # the snapshot raced the decode loop): render what exists,
            # up to the last stamp — the victim's partial work is
            # exactly what the merged failover trace must show
            t_end = float(evs[-1][1])
        t_admit = stamps.get(PHASE_ADMIT)
        t_first = stamps.get(PHASE_FIRST_TOKEN)
        args = {"id": rid, "outcome": tl.get("outcome"),
                "prompt_tokens": tl.get("prompt_tokens"),
                "new_tokens": tl.get("new_tokens")}
        q_end = t_admit if t_admit is not None else t_end
        events.append({
            "name": f"req {rid} queued", "cat": "request", "ph": "X",
            "ts": us(t_submit),
            "dur": round(max(0.0, q_end - t_submit) * 1e6, 3),
            "pid": pid, "tid": QUEUE_TID, "args": args,
        })
        trace = tl.get("trace")
        if trace:
            # cross-process flow STEP on this replica: bound inside the
            # request's first slice here (prefill when it reached a
            # slot, else the queued span) — the router's track holds
            # the flow's s/f ends
            bind_t0 = t_admit if t_admit is not None else t_submit
            bind_t1 = ((t_first if t_first is not None else t_end)
                       if t_admit is not None else q_end)
            events.append({
                "ph": "t", "cat": TRACE_CTX_CAT, "name": "trace",
                "id": str(trace),
                "ts": us(bind_t0 + max(0.0, bind_t1 - bind_t0) / 2.0),
                "pid": pid,
                "tid": (SLOT_TID_BASE + int(tl.get("slot") or 0))
                if t_admit is not None else QUEUE_TID,
            })
        if t_admit is None:
            continue  # never reached a slot (rejected / queue timeout)
        slot_tid = SLOT_TID_BASE + int(tl.get("slot") or 0)
        pf_end = t_first if t_first is not None else t_end
        events.append({
            "name": f"req {rid} prefill", "cat": "request", "ph": "X",
            "ts": us(t_admit),
            "dur": round(max(0.0, pf_end - t_admit) * 1e6, 3),
            "pid": pid, "tid": slot_tid, "args": args,
        })
        if t_first is None:
            continue
        events.append({
            "name": f"req {rid} decode", "cat": "request", "ph": "X",
            "ts": us(t_first),
            "dur": round(max(0.0, t_end - t_first) * 1e6, 3),
            "pid": pid, "tid": slot_tid, "args": args,
        })
        rode = [sync_by_id[s] for s in tl.get("syncs") or ()
                if s in sync_by_id]
        if not rode:
            continue
        # the flow: starts inside the request's decode span, steps
        # through every decode-step slice the request rode, finishes
        # in the last one — each ts lands MID-slice so the event binds
        # to the enclosing slice on (pid, tid). Flow events bind
        # globally by (cat, id), so the id carries the pid: two fleet
        # workers both serving a "request 3" must not cross-link.
        flow_id = flow_event_id(pid, rid)
        events.append({
            "ph": "s", "cat": _FLOW_CAT, "name": "req",
            "id": flow_id, "ts": us(t_first + 1e-6),
            "pid": pid, "tid": slot_tid,
        })
        for j, s in enumerate(rode):
            events.append({
                "ph": "f" if j == len(rode) - 1 else "t",
                "cat": _FLOW_CAT, "name": "req", "id": flow_id,
                "ts": us(float(s["t0"]) + float(s["dur"]) / 2.0),
                "pid": pid, "tid": int(s.get("tid") or 0),
                **({"bp": "e"} if j == len(rode) - 1 else {}),
            })
    return events


def flow_event_id(pid, rid) -> str:
    """The flow id for one request's trace arrows: pid-scoped, because
    Trace Event flow events join on (cat, id) ACROSS processes and
    per-process request ids collide in a merged fleet trace."""
    return f"{int(pid)}:{int(rid)}"


def _track_metadata(timelines, syncs, pid, label=None) -> list:
    """Track-naming metadata for one worker's request/sync events.
    `label` names the process track (omit when the caller — the fleet
    trace merge — already emitted its own process_name)."""
    events = []
    if label is not None:
        events.append({"ph": "M", "name": "process_name", "pid": pid,
                       "tid": 0, "args": {"name": label}})
    if timelines:
        events.append({"ph": "M", "name": "thread_name", "pid": pid,
                       "tid": QUEUE_TID,
                       "args": {"name": "serve queue"}})
    slots = sorted({int(tl.get("slot") or 0) for tl in timelines or ()
                    if tl.get("slot") is not None})
    for s in slots:
        events.append({"ph": "M", "name": "thread_name", "pid": pid,
                       "tid": SLOT_TID_BASE + s,
                       "args": {"name": f"serve slot {s}"}})
    for tid in sorted({int(s.get("tid") or 0) for s in syncs or ()}):
        events.append({"ph": "M", "name": "thread_name", "pid": pid,
                       "tid": tid, "args": {"name": "decode steps"}})
    return events


def engine_trace_events(eng=None) -> dict:
    """The local (single-process) request trace: every live engine's
    timeline ring + sync ring as one Trace Event JSON object. For the
    multi-replica view use `fleet.export_trace` — the shards carry the
    same timelines and the aggregator merges them with this module's
    builder, clock-aligned."""
    from . import engine as engine_mod
    engines = [eng] if eng is not None else engine_mod.get_engines()
    pid = os.getpid()
    events = []
    for i, e in enumerate(engines):
        timelines = e.timelines()
        syncs = e.sync_records()
        events.extend(_track_metadata(
            timelines, syncs, pid,
            f"serving engine {i} (pid {pid})"))
        events.extend(request_trace_events(timelines, syncs, pid))
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def export_trace(path: str, eng=None) -> str:
    """Write the local request trace JSON to `path` (open in Perfetto /
    chrome://tracing) and return the path."""
    with open(path, "w", encoding="utf-8") as f:
        json.dump(engine_trace_events(eng), f, separators=(",", ":"))
    return path


# ---- the fleet serving view ------------------------------------------------

#: per-shard cap on timelines/syncs riding a fleet publish — the shard
#: is rewritten whole every interval, so the serve line must stay small
_SHARD_TIMELINES = 64
_SHARD_SYNCS = 128


def fleet_serve_snapshot(max_timelines: int = _SHARD_TIMELINES,
                         max_syncs: int = _SHARD_SYNCS) -> "dict | None":
    """The `fleet_serve` shard line: this replica's live serving state
    (engine occupancy/queue/pages/RPS/TTFT percentiles, kv-cache bytes
    from the memory ledger, SLO attainment + burn) plus the recent
    request timelines and decode-step records the merged trace needs.
    None when no engine is running and no tracker is installed."""
    from . import engine as engine_mod
    engines = engine_mod.get_engines()
    tracker = get_tracker()
    if not engines and tracker is None:
        return None
    rps = 0.0
    queue_depth = occupancy = slots = 0
    pages_in_use = pages_total = 0
    pool_bytes = 0
    tok_s_parts = []
    ttfts = []
    finished = {}
    timelines = []
    active = []
    syncs = []
    for e in engines:
        r = e.report()
        rps += r.get("rps") or 0.0
        queue_depth += r["queue_depth"]
        occupancy += r["active"]
        slots += r["slots"]
        pages_in_use += r["pages_in_use"]
        pages_total += r["pages_total"]
        pool_bytes += r["pool_bytes"]
        for o, n in (r.get("finished") or {}).items():
            finished[o] = finished.get(o, 0) + n
        if r.get("decode_tok_s") is not None:
            tok_s_parts.append(r["decode_tok_s"])
        ttfts.extend(e.recent_ttfts())
        timelines.extend(e.timelines()[-max_timelines:])
        # IN-FLIGHT request timelines ride the shard too: when a
        # replica dies mid-request, its last published shard is the
        # only record of the work the victim had done — the merged
        # failover trace renders it as an open-ended track
        act = getattr(e, "active_timelines", None)
        if act is not None:
            active.extend(act()[-max_timelines:])
        syncs.extend(e.sync_records()[-max_syncs:])
    kv_bytes = pool_bytes
    try:
        from . import memory
        led = memory.get_ledger()
        rb = led.region_bytes() if led is not None else None
        if rb and isinstance(rb.get("regions"), dict) \
                and rb["regions"].get(memory.REGION_KV_CACHE) \
                is not None:
            kv_bytes = int(rb["regions"][memory.REGION_KV_CACHE])
    except Exception:
        pass
    slo_part = None
    if tracker is not None:
        v = tracker.current_verdict()
        slo_part = {
            "objectives": {
                obj: {"attainment": o["attainment"],
                      "burn_fast": o["burn_fast"],
                      "burn_slow": o["burn_slow"],
                      "breach": o["breach"]}
                for obj, o in v["objectives"].items()},
            "breaching": v["breaching"],
            "window_requests": v["window_requests"],
        }
    return {
        "engines": len(engines),
        # graceful drain in flight: the engine has stopped admitting
        # but is finishing its slots — the router/fleet view shows the
        # replica as draining rather than merely quiet
        "draining": any(getattr(e, "_draining", False)
                        for e in engines),
        "rps": round(rps, 3),
        "queue_depth": queue_depth,
        "occupancy": occupancy,
        "slots": slots,
        "pages_in_use": pages_in_use,
        "pages_total": pages_total,
        "page_util": round(pages_in_use / pages_total, 4)
        if pages_total else None,
        "kv_cache_bytes": kv_bytes,
        # measured decode rate, for the capacity model's bandwidth
        # wall (held against the roofline's bytes-per-token floor)
        "decode_tok_s": round(sum(tok_s_parts), 3)
        if tok_s_parts else None,
        "ttft_p50_s": engine_mod.pctile(ttfts, 0.5),
        "ttft_p99_s": engine_mod.pctile(ttfts, 0.99),
        "finished": finished,
        "slo": slo_part,
        "timelines": timelines[-max_timelines:],
        "active": active[-max_timelines:],
        "syncs": syncs[-max_syncs:],
    }


def serve_attainment_pct(serve: "dict | None") -> "float | None":
    """One per-replica SLO number for the fleet table: the WORST
    enabled objective's window attainment, percent. None without a
    tracker (or before any applicable request)."""
    slo_part = (serve or {}).get("slo")
    if not isinstance(slo_part, dict):
        return None
    atts = [o.get("attainment")
            for o in (slo_part.get("objectives") or {}).values()
            if o.get("attainment") is not None]
    return round(100.0 * min(atts), 2) if atts else None


# ---- reports ---------------------------------------------------------------

def _fmt_timeline(tl: dict) -> str:
    """One compact line per timeline: phase deltas from submit, with
    per-sync decode progress folded into a tokens trajectory."""
    events = tl.get("events") or []
    if not events:
        return f"req {tl.get('id')}: (no events)"
    t0 = float(events[0][1])
    parts = []
    decode_marks = 0
    for phase, t, info in events:
        if phase == PHASE_DECODE:
            decode_marks += 1
            continue
        tag = phase
        if phase == PHASE_TERMINAL and info:
            tag = f"{info.get('outcome', phase)}"
        parts.append(f"{tag}+{float(t) - t0:.3f}s")
    mid = f" [{decode_marks} decode syncs, " \
          f"{tl.get('new_tokens')} tok]" if decode_marks else ""
    return (f"req {tl.get('id')} ({tl.get('outcome')}): "
            + " -> ".join(parts) + mid)


def slo_report() -> str:
    """The /slo (and /statusz `== slo ==`) text block: config, per-
    objective attainment + burn, breach state, and the recent
    violating request ids with their timelines."""
    lines = ["== slo =="]
    tracker = get_tracker()
    if tracker is None:
        lines.append("no SLOTracker installed "
                     "(singa_tpu.slo.SLOTracker(SLOConfig(...))"
                     ".install())")
        return "\n".join(lines)
    cfg = tracker.config
    v = tracker.current_verdict()
    lines.append(
        f"objectives: {', '.join(cfg.enabled()) or 'none declared'}   "
        f"window {cfg.window_s:g}s   burn windows "
        f"{cfg.fast_window_s:g}s/{cfg.slow_window_s:g}s   "
        f"threshold {cfg.burn_threshold:g}x   "
        f"sustain {cfg.sustain}")
    lines.append(f"window requests: {v['window_requests']}   "
                 f"evaluations: {v['evaluations']}   breaching: "
                 f"{', '.join(v['breaching']) or 'none'}")
    for obj, o in v["objectives"].items():
        att = f"{100.0 * o['attainment']:.2f}%" \
            if o["attainment"] is not None else "no data"
        bf = f"{o['burn_fast']:.2f}x" \
            if o["burn_fast"] is not None else "-"
        bs = f"{o['burn_slow']:.2f}x" \
            if o["burn_slow"] is not None else "-"
        state = "BREACH" if o["breach"] else (
            "burning" if o["burning"] else "ok")
        lines.append(
            f"  {obj:<16} target {o['target']:g} "
            f"(frac {o['target_fraction']:g})  attainment {att} "
            f"({o['good']}/{o['total']})  burn {bf}/{bs}  {state}")
    viol = tracker.violations()
    if viol:
        lines.append(f"recent violations ({len(viol)}):")
        for rec in viol[-8:]:
            objs = ",".join(rec["objectives"])
            lines.append(f"  req {rec['id']} [{objs}] "
                         f"ttft={rec['ttft_s']} total={rec['total_s']}")
            tl = rec.get("timeline")
            if tl:
                lines.append("    " + _fmt_timeline(tl))
            attr = rec.get("attr")
            if attr:
                ranked = sorted(attr.items(), key=lambda kv: -kv[1])
                lines.append("    attr: " + " ".join(
                    f"{k}={v:.4f}s" for k, v in ranked))
    else:
        lines.append("recent violations: none")
    return "\n".join(lines)


def slo_json() -> dict:
    """The /slo?json=1 body: config + fresh verdict + violations (with
    timelines)."""
    tracker = get_tracker()
    if tracker is None:
        return {"installed": False}
    return {
        "installed": True,
        "config": tracker.config.snapshot(),
        "verdict": tracker.current_verdict(),
        "violations": tracker.violations(),
    }


# ---- CLI: the SLO degradation A/B ------------------------------------------
# `--ab` runs two in-process serving legs over one seeded Poisson
# workload: a clean leg (attainment must hold at 100%) and a degraded
# leg with a FaultPlan delay injected at `serving.engine_step` (every
# decode sync stalls, so queued requests' TTFT degrades), asserting the
# burn-rate verdict fires within K evaluation windows, /healthz's
# monitor reflects it, and the merged fleet trace flow-links a chosen
# request to the decode-step slices it rode.

def _ab_build_model(args):
    import numpy as np

    from . import models, tensor
    from .device import best_device
    dev = best_device()
    T = args.prompt_hi + args.new_hi
    m = models.create_model(
        "gpt", vocab_size=args.vocab, max_seq=T, dim=args.dim,
        num_heads=4, num_layers=args.layers)
    ids = tensor.from_numpy(
        np.random.RandomState(0).randint(
            0, args.vocab, (2, 8)).astype(np.int32), device=dev)
    m.compile([ids], is_train=False, use_graph=False)
    m.eval()
    return m, T


def _ab_leg(args, m, T, inject: bool, fleet_dir: str) -> dict:
    import numpy as np

    from . import engine as engine_mod
    from . import fleet, health, resilience
    cfg = SLOConfig(
        ttft_p99_s=args.slo_ttft, availability=args.slo_availability,
        window_s=args.slow_window, fast_window_s=args.fast_window,
        slow_window_s=args.slow_window,
        burn_threshold=args.burn_threshold, sustain=args.sustain,
        # evaluation is driven MANUALLY on the harness cadence below,
        # so "evaluation windows" is a countable quantity; min_requests
        # keeps a small-sample blip from reading as a burn
        min_requests=5, eval_interval_s=1e9)
    mon = health.HealthMonitor(policy="warn")
    health.set_active_monitor(mon)
    writer = fleet.start_shard_writer(fleet_dir, interval_s=0)
    agg = fleet.install_aggregator(fleet_dir, stale_after_s=60.0)
    if inject:
        plan = resilience.FaultPlan()
        plan.delay("serving.engine_step", args.delay, times=10 ** 9)
        resilience.install_fault_plan(plan)
    rng = np.random.RandomState(args.seed)
    arrivals = np.cumsum(rng.exponential(1.0 / args.rps, args.requests))
    prompts = [rng.randint(0, args.vocab, (rng.randint(
        args.prompt_lo, args.prompt_hi + 1),)).astype(np.int32)
        for _ in range(args.requests)]
    new_lens = rng.randint(args.new_lo, args.new_hi + 1, args.requests)
    eng = engine_mod.ServingEngine(
        m, max_slots=args.slots, page_size=8, max_ctx=T,
        steps_per_sync=2, queue_limit=4 * args.requests).start()
    rec = {"inject": inject, "delay_s": args.delay if inject else 0.0}
    try:
        # warm the buckets outside the measured workload — the tracker
        # installs AFTER, so compile-time TTFTs never burn the budget
        for b in sorted({eng._bucket(len(p)) for p in prompts}):
            w = eng.submit(np.ones(min(b, T - 2), np.int32), 2)
            if not w.wait(300):
                raise RuntimeError(f"warmup bucket {b} stalled")
        tracker = SLOTracker(cfg).install()
        # one long-running request keeps decode syncs (and the injected
        # delay) flowing while the short ones queue behind them
        anchor = eng.submit(prompts[0], int(args.new_hi))
        t0 = time.perf_counter()
        handles = [anchor]
        for i in range(1, args.requests):
            dt = t0 + float(arrivals[i]) - time.perf_counter()
            if dt > 0:
                time.sleep(dt)
            handles.append(eng.submit(prompts[i], int(new_lens[i])))
        # drive the evaluation windows on a fixed cadence; the verdict
        # clock starts at the first window that OBSERVES the burn
        # (both windows over threshold with enough samples) — the
        # acceptance bound says the multi-window gate convicts within
        # `sustain + 3` burning windows, it does not measure how long
        # the workload takes to produce samples
        breach_eval = None
        burning_evals = 0
        idle_evals = 0
        t_first_violation = None
        deadline = time.monotonic() + 600
        while time.monotonic() < deadline:
            time.sleep(args.eval_interval)
            v = tracker.evaluate()
            if t_first_violation is None and tracker.violations():
                t_first_violation = time.monotonic()
            if any(o["burning"] or o["breach"]
                   for o in v["objectives"].values()):
                burning_evals += 1
            if v["breaching"] and breach_eval is None:
                breach_eval = burning_evals
                rec["violation_to_breach_s"] = round(
                    time.monotonic() - t_first_violation, 3) \
                    if t_first_violation else None
            if all(h.done() for h in handles):
                idle_evals += 1
                if breach_eval is not None or not inject \
                        or idle_evals > 40:
                    break
        stuck = [h.id for h in handles if not h.wait(600)]
        if stuck:
            raise RuntimeError(f"requests {stuck} stalled")
        v = tracker.evaluate()
        att = {obj: o["attainment"]
               for obj, o in v["objectives"].items()}
        rec["attainment"] = {
            k: round(100.0 * a, 2) if a is not None else None
            for k, a in att.items()}
        rec["breaching"] = v["breaching"]
        rec["breach_after_evals"] = breach_eval
        rec["health_status"] = mon.verdict()["status"]
        rec["violations"] = len(tracker.violations())
        # the merged trace, from the fleet surface (clock handshake)
        writer.publish()
        agg.poll()
        trace = agg.trace_events()
        rec["trace"] = _check_flow_trace(trace, eng)
    finally:
        eng.stop()
        reset()
        fleet.uninstall()
        resilience.clear_fault_plan()
        health.set_active_monitor(None)
    return rec


def _check_flow_trace(trace: dict, eng) -> dict:
    """Schema + flow-link validation of a merged trace: X slices carry
    ts/dur/tid, and a chosen request's flow events (s -> t* -> f) land
    inside decode-step slices on the same pid."""
    events = trace.get("traceEvents", [])
    xs = [e for e in events if e.get("ph") == "X"]
    schema_ok = (isinstance(events, list) and bool(events)
                 and all(isinstance(e.get("name"), str)
                         and "ph" in e and "pid" in e for e in events)
                 and all("ts" in e and "dur" in e and "tid" in e
                         for e in xs))
    # a request that rode at least one decode sync
    chosen = next((tl for tl in eng.timelines()
                   if tl.get("syncs") and tl.get("outcome")
                   == "completed"), None)
    flow_ok = False
    flow_id = None
    if chosen is not None:
        flow_id = flow_event_id(os.getpid(), chosen["id"])
        flows = [e for e in events if e.get("cat") == _FLOW_CAT
                 and e.get("id") == flow_id]
        steps = [e for e in flows if e.get("ph") in ("t", "f")]
        step_slices = [e for e in xs
                       if e.get("name") == "serving.engine_step"]

        def inside(ev):
            return any(s["pid"] == ev["pid"] and s["tid"] == ev["tid"]
                       and s["ts"] <= ev["ts"] <= s["ts"] + s["dur"]
                       for s in step_slices)

        flow_ok = (any(e.get("ph") == "s" for e in flows)
                   and bool(steps) and all(inside(e) for e in steps))
    return {"schema_ok": bool(schema_ok), "events": len(events),
            "flow_request_id": flow_id, "flow_ok": bool(flow_ok)}


def _ab_main(args) -> int:
    import tempfile

    m, T = _ab_build_model(args)
    work = tempfile.mkdtemp(prefix="singa_slo_ab_")
    rec = {"requests": args.requests, "rps": args.rps,
           "delay_s": args.delay, "slo_ttft_s": args.slo_ttft,
           "burn_threshold": args.burn_threshold,
           "sustain": args.sustain, "max_evals": args.max_evals,
           "ok": False}
    try:
        rec["clean"] = _ab_leg(args, m, T, inject=False,
                               fleet_dir=os.path.join(work, "clean"))
        rec["degraded"] = _ab_leg(
            args, m, T, inject=True,
            fleet_dir=os.path.join(work, "degraded"))
        clean, deg = rec["clean"], rec["degraded"]
        clean_att = clean["attainment"].get("ttft_p99")
        deg_att = deg["attainment"].get("ttft_p99")
        rec["ok"] = bool(
            clean_att == 100.0
            and not clean["breaching"]
            and clean["health_status"] in ("idle", "ok")
            and deg_att is not None and deg_att < 100.0
            and "ttft_p99" in deg["breaching"]
            and deg["breach_after_evals"] is not None
            and deg["breach_after_evals"] <= args.max_evals
            and deg["health_status"] == "warn"
            and clean["trace"]["schema_ok"]
            and deg["trace"]["schema_ok"]
            and deg["trace"]["flow_ok"])
    finally:
        import shutil
        shutil.rmtree(work, ignore_errors=True)
    out = os.path.abspath(args.out)
    with open(out, "w", encoding="utf-8") as f:
        json.dump(rec, f, indent=1)
        f.write("\n")
    print(json.dumps(rec, indent=1))
    return 0 if rec["ok"] else 1


def main(argv=None) -> int:
    import argparse
    p = argparse.ArgumentParser(
        prog="python -m singa_tpu.slo",
        description="serving-SLO harness (clean vs degraded burn A/B)")
    p.add_argument("--ab", action="store_true",
                   help="run the SLO degradation A/B")
    p.add_argument("--out", default="SLO_r01.json")
    p.add_argument("--requests", type=int, default=16)
    p.add_argument("--rps", type=float, default=6.0)
    p.add_argument("--seed", type=int, default=7)
    p.add_argument("--dim", type=int, default=64)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--vocab", type=int, default=211)
    p.add_argument("--slots", type=int, default=4)
    p.add_argument("--prompt-lo", type=int, default=4)
    p.add_argument("--prompt-hi", type=int, default=12)
    p.add_argument("--new-lo", type=int, default=4)
    p.add_argument("--new-hi", type=int, default=24)
    p.add_argument("--delay", type=float, default=0.4,
                   help="FaultPlan delay per decode sync (degraded leg)")
    p.add_argument("--slo-ttft", type=float, default=0.25,
                   help="p99 TTFT target: above the clean TTFT, below "
                        "the injected delay")
    p.add_argument("--slo-availability", type=float, default=0.9)
    p.add_argument("--fast-window", type=float, default=2.0)
    p.add_argument("--slow-window", type=float, default=20.0)
    p.add_argument("--burn-threshold", type=float, default=2.0)
    p.add_argument("--sustain", type=int, default=2)
    p.add_argument("--eval-interval", type=float, default=0.1)
    p.add_argument("--max-evals", type=int, default=None,
                   help="acceptance bound on evaluations-to-breach "
                        "(default: sustain + 3, i.e. within 5 windows "
                        "at the default sustain)")
    args = p.parse_args(argv)
    if args.max_evals is None:
        args.max_evals = args.sustain + 3
    if args.ab:
        return _ab_main(args)
    p.error("pass --ab")
    return 2


__all__ = [
    "REQUEST_PHASES", "SLO_OBJECTIVES", "LATENCY_ATTR",
    "SLOConfig", "SLOTracker", "request_latency_sample",
    "objective_good", "attainment", "burn_rate", "phase_durations",
    "attribute_timeline", "attribute_route", "note_attribution",
    "tail_records", "tail_summary", "tail_report", "tail_json",
    "TailCollector", "install_tail", "get_tail", "tail_reset",
    "install", "uninstall", "get_tracker", "reset", "note_decode",
    "request_trace_events", "engine_trace_events", "export_trace",
    "flow_event_id",
    "fleet_serve_snapshot", "serve_attainment_pct",
    "slo_report", "slo_json",
]

if __name__ == "__main__":
    import sys

    # run under the CANONICAL module (not the runpy __main__ alias): the
    # CLI installs module singletons (tracker, fleet aggregator) that
    # diag/fleet handlers reach via `import singa_tpu.slo`
    from singa_tpu.slo import main as _main
    sys.exit(_main())
