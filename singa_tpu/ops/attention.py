"""Attention ops: fused flash attention (Pallas), ring attention (sequence
parallel over a mesh axis), and paged decode attention (the serving
engine's ragged KV-cache path).

No counterpart exists in the reference — it has no attention op at all
(SURVEY.md §2.3: transformers enter only via ONNX import) — but long-context
is first-class here. Layout is (batch, heads, seq, head_dim) throughout.

Three tiers, same math:
  1. `attention_reference`  — jnp, O(S^2) memory; ground truth for tests.
  2. `flash_attention`      — Pallas online-softmax kernel, O(S) memory,
                              custom_vjp with blockwise recompute backward.
  3. `ring_attention`       — flash over sequence shards on a mesh axis;
                              K/V blocks rotate via lax.ppermute so each
                              ICI hop overlaps with the local block matmul
                              (the jax-native form of the RDMA ring pattern
                              in /opt/skills/guides/pallas_guide.md §18).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from .. import _compat  # noqa: F401  (installs jax.shard_map on old jax)

_NEG_INF = -1e30


def _causal_mask(sq, sk, q_off=0, k_off=0, dtype=jnp.float32):
    q_pos = q_off + lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
    k_pos = k_off + lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
    return jnp.where(k_pos > q_pos, _NEG_INF, 0.0).astype(dtype)


# ======================= 1. reference ====================================

def attention_reference(q, k, v, causal=False, scale=None):
    """q,k,v: (B, H, S, D). Returns (B, H, Sq, D)."""
    d = q.shape[-1]
    scale = scale if scale is not None else d ** -0.5
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        s = s + _causal_mask(q.shape[2], k.shape[2], dtype=s.dtype)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


# ======================= 2. flash attention ==============================
# Online-softmax over K blocks; the kernel keeps one (Bq, D) accumulator,
# running row-max m and row-sum l in VMEM scratch. Backward recomputes
# blockwise (no S matrix ever materialized).

# Measured on v5e (fp32, differential timing): at S=4096, 128x128 tiles
# run 30.6 ms vs 4.3 ms at 1024x1024 — per-grid-step overhead dominates
# small tiles, and a (1024,64) tile is still only 256 KB of VMEM. At
# S<=512 inside a full model, 256 beats 512 (~8%) — VMEM pressure against
# the surrounding fused ops. None = pick by sequence length.
DEFAULT_BLOCK_Q = None
DEFAULT_BLOCK_K = None


def _default_block(s):
    import os
    env = os.environ.get("SINGA_FLASH_BLOCK")
    if env:
        return int(env)
    return 1024 if s >= 1024 else 256


def _fit_block(s, target, floor=128):
    """Largest block <= target that tiles s evenly on 8-sublane alignment.
    None when nothing >= `floor` divides s (caller falls back to the XLA
    reference path) — tiles below ~128 are per-grid-step-overhead bound
    and run far slower than the O(S^2) XLA path."""
    b = min(target, s)
    b -= b % 8
    floor = min(floor, s)
    while b >= floor:
        if s % b == 0:
            return b
        b -= 8
    return None


try:  # import here so CPU-only environments still import the module
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PALLAS = True
except ImportError:  # pragma: no cover
    _HAS_PALLAS = False


# TPU Pallas needs the last two block dims (sublane, lane) aligned; scalar
# per-row stats (lse, delta, running m/l) are carried as (rows, _STAT_LANES)
# with the value replicated across lanes — rows on sublanes means reading
# [:, :1] yields the column vector with no relayout.
_STAT_LANES = 8


def _maybe_when(cond, fn):
    """pl.when for traced predicates; plain call for static True."""
    if cond is True:
        fn()
    else:
        pl.when(cond)(fn)


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                      acc_ref, m_ref, l_ref, *scratch,
                      nk, block_q, block_k, causal, hoist_mask=False):
    """Grid: (batch*heads, q_blocks, k_blocks) — K/V blocks STREAM through
    VMEM one (block_k, D) tile at a time (no whole-row residency, so
    sequence length is bounded by HBM, not VMEM). The online-softmax state
    (acc, m, l) lives in VMEM scratch, which persists across the k grid
    dimension. CONTRACT: the grid must stay FULLY sequential (no
    dimension_semantics 'parallel' on any dim) — hoist_mask initializes
    its scratch at program_id(0) == 0 and every later bh step reads it,
    so a parallelized bh dimension would read uninitialized VMEM."""
    qi = pl.program_id(1)
    kb = pl.program_id(2)

    # hoist_mask (static; only when nq == nk == 1, e.g. S <= 1024 at the
    # default block): the causal mask is identical for every grid step,
    # so it is built ONCE into a persistent VMEM scratch instead of
    # paying iota+compare+select on the full score tile per step
    if hoist_mask:
        mask_ref = scratch[0]          # bf16: -1e30 is representable
        # (8-bit exponent), and halves the persistent VMEM cost

        @pl.when(pl.program_id(0) == 0)
        def _mask_init():
            mask_ref[...] = _causal_mask(block_q, block_k,
                                         dtype=mask_ref.dtype)

    @pl.when(kb == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # causal: skip K blocks strictly above the diagonal of this Q block.
    # COMPUTE is gated here; the DMA for those blocks is skipped too —
    # _causal_clamp maps their BlockSpec index to the diagonal block, and
    # Pallas TPU elides the copy when the block index doesn't change
    # between grid steps.
    needed = (kb * block_k <= qi * block_q + block_q - 1) if causal else True

    def _update():
        # dots run in the INPUT dtype (bf16 inputs → native MXU rate;
        # upcasting to f32 first would run the matmul at the ~4x-slower
        # fp32 rate) and accumulate f32 via preferred_element_type; the
        # softmax/stats stay in f32. q arrives PRE-SCALED (the wrapper
        # folds the softmax scale into q, where XLA fuses it for free —
        # an in-kernel multiply would cost a VPU pass over the full
        # score tile every grid step).
        q = q_ref[0]                                   # (Bq, D), scaled
        k_blk = k_ref[0]                               # (Bk, D)
        v_blk = v_ref[0]
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32)
        if hoist_mask:
            s = s + mask_ref[...]
        elif causal:
            s = s + _causal_mask(block_q, block_k, q_off=qi * block_q,
                                 k_off=kb * block_k)
        m_prev = m_ref[...][:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        l_new = l_ref[...][:, :1] * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * corr + jnp.dot(
            p.astype(v_blk.dtype), v_blk,
            preferred_element_type=jnp.float32)
        m_ref[...] = jnp.broadcast_to(m_new, (block_q, _STAT_LANES))
        l_ref[...] = jnp.broadcast_to(l_new, (block_q, _STAT_LANES))

    _maybe_when(needed, _update)

    @pl.when(kb == nk - 1)
    def _finish():
        l = jnp.maximum(l_ref[...][:, :1], 1e-20)
        o_ref[0] = (acc_ref[...] / l).astype(o_ref.dtype)
        lse_ref[0] = jnp.broadcast_to(m_ref[...][:, :1] + jnp.log(l),
                                      (block_q, _STAT_LANES))


def _causal_kv_map(causal, block_q, block_k, nk):
    """K/V BlockSpec index map for grids with kb innermost after the q
    block index. Causal: kb is CLAMPED to this q block's diagonal block,
    so every fully-masked step re-addresses the last needed block and
    Pallas skips the DMA (the copy only fires when the block index
    changes) — masked K/V tiles are neither computed nor streamed."""
    if not causal:
        return lambda i, j, kb: (i, kb, 0)

    def kmap(i, j, kb):
        last = jnp.minimum(((j + 1) * block_q - 1) // block_k, nk - 1)
        return (i, jnp.minimum(kb, last), 0)

    return kmap


def _causal_q_map(causal, block_q, block_k):
    """Q-side BlockSpec index map for the dK/dV grid (bh, kb, j): causal
    clamps j UP to the first unmasked q block for kb, so the leading
    masked steps address the same tile and their DMA is elided."""
    if not causal:
        return lambda i, kb, j: (i, j, 0)

    def qmap(i, kb, j):
        first = (kb * block_k) // block_q
        return (i, jnp.maximum(j, first), 0)

    return qmap


def _flash_fwd_pallas(q, k, v, causal, scale, block_q, block_k, interpret):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    bh = b * h
    # fold the softmax scale into q here: XLA fuses the multiply into
    # whatever produced q, so the kernel never spends a VPU pass on it
    qf = (q * scale).astype(q.dtype).reshape(bh, sq, d)
    kf = k.reshape(bh, sk, d)
    vf = v.reshape(bh, sk, d)
    nk = sk // block_k
    nq = sq // block_q
    grid = (bh, nq, nk)
    # single-tile causal grids reuse one mask every step; cap the
    # persistent scratch at 2MB so an env-forced giant block can't eat
    # the VMEM budget the streamed tiles need
    hoist = (causal and nq == 1 and nk == 1
             and block_q * block_k * 2 <= 2 * 1024 * 1024)
    kernel = functools.partial(
        _flash_fwd_kernel, nk=nk, block_q=block_q, block_k=block_k,
        causal=causal, hoist_mask=hoist)
    kvmap = _causal_kv_map(causal, block_q, block_k, nk)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j, kb: (i, j, 0)),
            pl.BlockSpec((1, block_k, d), kvmap),
            pl.BlockSpec((1, block_k, d), kvmap),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j, kb: (i, j, 0)),
            pl.BlockSpec((1, block_q, _STAT_LANES),
                         lambda i, j, kb: (i, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, sq, _STAT_LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, _STAT_LANES), jnp.float32),
            pltpu.VMEM((block_q, _STAT_LANES), jnp.float32),
        ] + ([pltpu.VMEM((block_q, block_k), jnp.bfloat16)]
             if hoist else []),
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(b, h, sq, d), lse[:, :, 0].reshape(b, h, sq)


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, dq_acc, *, nk, block_q, block_k, causal,
                         scale):
    """Grid (bh, q_blocks, k_blocks): accumulate dQ over streamed K/V."""
    qi = pl.program_id(1)
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    needed = (kb * block_k <= qi * block_q + block_q - 1) if causal else True

    def _update():
        # native-dtype MXU dots (see fwd kernel); ds is rounded to the
        # input dtype for its matmul, standard flash-2 practice. q
        # arrives PRE-SCALED, so s matches the forward's lse directly;
        # the true dL/dq = scale * ds @ k is applied at _finish.
        q = q_ref[0]
        k_blk = k_ref[0]
        v_blk = v_ref[0]
        do = do_ref[0]
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32)
        if causal:
            s = s + _causal_mask(block_q, block_k, q_off=qi * block_q,
                                 k_off=kb * block_k)
        p = jnp.exp(s - lse_ref[0][:, :1])
        dp = jnp.dot(do, v_blk.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0][:, :1])
        dq_acc[...] += jnp.dot(ds.astype(k_blk.dtype), k_blk,
                               preferred_element_type=jnp.float32)

    _maybe_when(needed, _update)

    @pl.when(kb == nk - 1)
    def _finish():
        dq_ref[0] = (dq_acc[...] * scale).astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          dk_ref, dv_ref, dk_acc, dv_acc, *, nq, block_q,
                          block_k, causal):
    """Grid (bh, k_blocks, q_blocks): accumulate dK/dV over streamed Q."""
    kb = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    needed = (qi * block_q + block_q - 1 >= kb * block_k) if causal else True

    def _update():
        # native-dtype MXU dots; p/ds rounded to the input dtype for
        # their matmuls (flash-2 practice). q arrives PRE-SCALED, so
        # dk = ds.T @ q_scaled IS the true scale * ds.T @ q — no extra
        # multiply anywhere.
        q = q_ref[0]
        k_blk = k_ref[0]
        v_blk = v_ref[0]
        do = do_ref[0]
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32)
        if causal:
            s = s + _causal_mask(block_q, block_k, q_off=qi * block_q,
                                 k_off=kb * block_k)
        p = jnp.exp(s - lse_ref[0][:, :1])                 # (Bq, Bk)
        dv_acc[...] += jnp.dot(p.astype(do.dtype).T, do,
                               preferred_element_type=jnp.float32)
        dp = jnp.dot(do, v_blk.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0][:, :1])
        dk_acc[...] += jnp.dot(ds.astype(q.dtype).T, q,
                               preferred_element_type=jnp.float32)

    _maybe_when(needed, _update)

    @pl.when(qi == nq - 1)
    def _finish():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _flash_bwd_fused_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref,
                            delta_ref, dq_ref, dk_ref, dv_ref,
                            dq_acc, dk_acc, dv_acc, *, nq, nk, block_q,
                            block_k, causal, scale):
    """Single-pass backward: grid (bh, k_blocks, q_blocks) computes
    s/p/ds ONCE per tile pair and emits all three gradients — the split
    dq/dkv pair recomputes the two largest matmuls (s and dp) and the
    exp, and streams every q/k/v/do tile twice. dQ accumulates in a
    persistent (Sq, D) VMEM scratch (TPU grid iteration is sequential,
    so the scratch survives the whole (nk, nq) sweep of one bh row);
    callers gate this kernel on that scratch fitting VMEM."""
    kb = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when((kb == 0) & (j == 0))
    def _init_dq():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    @pl.when(j == 0)
    def _init_dkv():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    needed = (j * block_q + block_q - 1 >= kb * block_k) if causal \
        else True
    # the q-side window this step addresses (mirrors _causal_q_map's
    # clamp) — masked steps re-address the first needed block so their
    # unconditional dq store writes that block's current partial
    if causal:
        eff_j = jnp.maximum(j, (kb * block_k) // block_q)
    else:
        eff_j = j
    rows = pl.dslice(eff_j * block_q, block_q)

    def _update():
        q = q_ref[0]                  # pre-scaled (see fwd kernel)
        k_blk = k_ref[0]
        v_blk = v_ref[0]
        do = do_ref[0]
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32)
        if causal:
            s = s + _causal_mask(block_q, block_k, q_off=j * block_q,
                                 k_off=kb * block_k)
        p = jnp.exp(s - lse_ref[0][:, :1])                 # (Bq, Bk)
        dv_acc[...] += jnp.dot(p.astype(do.dtype).T, do,
                               preferred_element_type=jnp.float32)
        dp = jnp.dot(do, v_blk.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0][:, :1])
        dk_acc[...] += jnp.dot(ds.astype(q.dtype).T, q,
                               preferred_element_type=jnp.float32)
        dq_acc[rows, :] += jnp.dot(ds.astype(k_blk.dtype), k_blk,
                                   preferred_element_type=jnp.float32)

    _maybe_when(needed, _update)

    # dq: store the addressed window's partial every step — its LAST
    # flush for window j happens at this row's diagonal block (causal;
    # kb = nk-1 otherwise), where the accumulation is complete
    dq_ref[0] = (dq_acc[rows, :] * scale).astype(dq_ref.dtype)

    @pl.when(j == nq - 1)
    def _finish_dkv():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


# dq scratch cap for the fused backward: (Sq, D) f32 must fit scoped
# VMEM alongside the streamed tiles (~16 MB total) — 4 MB covers
# S=8192 at D=128; longer sequences fall back to the split kernels.
_FUSED_DQ_BYTES_CAP = 4 * 1024 * 1024


def _flash_bwd_fused(qf, kf, vf, dof, lsef, delta, causal, scale,
                     block_q, block_k, interpret, shapes):
    b, h, sq, sk, d = shapes
    bh = b * h
    nq, nk = sq // block_q, sk // block_k
    kvmap_kq = lambda i, kb, j: (i, kb, 0)
    qmap = _causal_q_map(causal, block_q, block_k)
    stat_spec = pl.BlockSpec((1, block_q, _STAT_LANES), qmap)
    dq, dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_fused_kernel, nq=nq, nk=nk,
                          block_q=block_q, block_k=block_k,
                          causal=causal, scale=scale),
        grid=(bh, nk, nq),
        in_specs=[
            pl.BlockSpec((1, block_q, d), qmap),
            pl.BlockSpec((1, block_k, d), kvmap_kq),
            pl.BlockSpec((1, block_k, d), kvmap_kq),
            pl.BlockSpec((1, block_q, d), qmap),
            stat_spec,
            stat_spec,
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), qmap),
            pl.BlockSpec((1, block_k, d), kvmap_kq),
            pl.BlockSpec((1, block_k, d), kvmap_kq),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), qf.dtype),
            jax.ShapeDtypeStruct((bh, sk, d), kf.dtype),
            jax.ShapeDtypeStruct((bh, sk, d), vf.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((sq, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        interpret=interpret,
    )(qf, kf, vf, dof, lsef, delta)
    return dq, dk, dv


def _flash_bwd_stats(o, lse, do):
    """(lsef, delta) lane-broadcast stat tensors for the backward kernels;
    loop-invariant across ring hops, so callers may precompute once."""
    b, h, sq, _ = o.shape
    bh = b * h
    stat = (bh, sq, _STAT_LANES)
    lsef = jnp.broadcast_to(lse.reshape(bh, sq)[:, :, None], stat)
    # delta = rowsum(do * o): cheap elementwise, leave to XLA fusion
    delta = jnp.broadcast_to(
        jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                axis=-1).reshape(bh, sq)[:, :, None], stat)
    return lsef, delta


def _flash_bwd_pallas(q, k, v, o, lse, do, causal, scale, block_q, block_k,
                      interpret, stats=None):
    """Pallas flash backward: dQ and dK/dV kernels with streamed tiles."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    bh = b * h
    # q pre-scaled, as in the forward (kernels consume scaled q; dq gets
    # its own scale factor at _finish, dk inherits it from q itself)
    qf = (q * scale).astype(q.dtype).reshape(bh, sq, d)
    kf, vf = (a.reshape(bh, -1, d) for a in (k, v))
    dof = do.reshape(bh, sq, d)
    lsef, delta = stats if stats is not None else _flash_bwd_stats(o, lse,
                                                                   do)
    if sq * d * 4 <= _FUSED_DQ_BYTES_CAP:
        dq, dk, dv = _flash_bwd_fused(
            qf, kf, vf, dof, lsef, delta, causal, scale, block_q,
            block_k, interpret, (b, h, sq, sk, d))
        return (dq.reshape(b, h, sq, d), dk.reshape(b, h, sk, d),
                dv.reshape(b, h, sk, d))
    nq, nk = sq // block_q, sk // block_k
    kvmap = _causal_kv_map(causal, block_q, block_k, nk)
    qmap = _causal_q_map(causal, block_q, block_k)
    stat_spec_q = pl.BlockSpec((1, block_q, _STAT_LANES),
                               lambda i, j, kb: (i, j, 0))
    stat_spec_kq = pl.BlockSpec((1, block_q, _STAT_LANES), qmap)

    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, nk=nk, block_q=block_q,
                          block_k=block_k, causal=causal, scale=scale),
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j, kb: (i, j, 0)),
            pl.BlockSpec((1, block_k, d), kvmap),
            pl.BlockSpec((1, block_k, d), kvmap),
            pl.BlockSpec((1, block_q, d), lambda i, j, kb: (i, j, 0)),
            stat_spec_q,
            stat_spec_q,
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda i, j, kb: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(qf, kf, vf, dof, lsef, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, nq=nq, block_q=block_q,
                          block_k=block_k, causal=causal),
        grid=(bh, nk, nq),
        in_specs=[
            pl.BlockSpec((1, block_q, d), qmap),
            pl.BlockSpec((1, block_k, d), lambda i, kb, j: (i, kb, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, kb, j: (i, kb, 0)),
            pl.BlockSpec((1, block_q, d), qmap),
            stat_spec_kq,
            stat_spec_kq,
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda i, kb, j: (i, kb, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, kb, j: (i, kb, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, sk, d), v.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        interpret=interpret,
    )(qf, kf, vf, dof, lsef, delta)
    return (dq.reshape(b, h, sq, d), dk.reshape(b, h, sk, d),
            dv.reshape(b, h, sk, d))


def _flash_bwd_blockwise(q, k, v, o, lse, do, causal, scale, block_k):
    """Recompute-based backward, scanned over K blocks (O(S) memory)."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    qs = q.astype(jnp.float32) * scale
    do_ = do.astype(jnp.float32)
    # delta = rowsum(do * o)  (standard flash-2 backward term)
    delta = jnp.sum(do_ * o.astype(jnp.float32), axis=-1)  # (B,H,Sq)

    nkb = sk // block_k
    kb_idx = jnp.arange(nkb)

    def per_kblock(kb):
        k_blk = lax.dynamic_slice_in_dim(k, kb * block_k, block_k, axis=2)
        v_blk = lax.dynamic_slice_in_dim(v, kb * block_k, block_k, axis=2)
        s = jnp.einsum("bhqd,bhkd->bhqk", qs, k_blk.astype(jnp.float32))
        if causal:
            s = s + _causal_mask(sq, block_k, 0, kb * block_k)[None, None]
        p = jnp.exp(s - lse[..., None])                    # (B,H,Sq,Bk)
        dv = jnp.einsum("bhqk,bhqd->bhkd", p, do_)
        dp = jnp.einsum("bhqd,bhkd->bhqk", do_, v_blk.astype(jnp.float32))
        ds = p * (dp - delta[..., None])
        dk = jnp.einsum("bhqk,bhqd->bhkd", ds, qs) * 1.0
        dq_part = jnp.einsum("bhqk,bhkd->bhqd", ds,
                             k_blk.astype(jnp.float32))
        return dq_part, dk, dv

    def scan_body(dq_acc, kb):
        dq_part, dk, dv = per_kblock(kb)
        return dq_acc + dq_part, (dk, dv)

    dq, (dks, dvs) = lax.scan(scan_body,
                              jnp.zeros(q.shape, jnp.float32), kb_idx)
    dk = jnp.moveaxis(dks, 0, 2).reshape(b, h, sk, d)
    dv = jnp.moveaxis(dvs, 0, 2).reshape(b, h, sk, d)
    return (dq * scale).astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(q, k, v, causal=False, scale=None,
                    block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K,
                    interpret=None):
    """Fused attention; q,k,v (B,H,S,D). Falls back to the reference path
    when shapes don't tile (S % block != 0) or Pallas is unavailable."""
    out, _ = _flash_fwd(q, k, v, causal, scale, block_q, block_k,
                        interpret)
    return out


def _resolve(scale, d, interpret):
    scale = scale if scale is not None else d ** -0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return scale, interpret


def _resolve_blocks(sq, sk, block_q, block_k):
    """(bq, bk, ok): pick tiles that divide the sequence on 8-sublane
    alignment (TPU lowering constraint). None selects the largest evenly-
    tiling block at or below the measured per-sequence-length default
    (so S=384 runs the kernel at 192 instead of falling back); an EXPLICIT
    block that doesn't tile keeps the old contract: ok=False -> reference
    path."""
    if block_q is None:
        bq = _fit_block(sq, _default_block(sq))
    else:
        bq = min(block_q, sq)
        bq = bq if (sq % bq == 0 and bq % 8 == 0) else None
    if block_k is None:
        bk = _fit_block(sk, _default_block(sk))
    else:
        bk = min(block_k, sk)
        bk = bk if (sk % bk == 0 and bk % 8 == 0) else None
    ok = bq is not None and bk is not None
    return (bq or 0), (bk or 0), ok


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    d = q.shape[-1]
    scale, interpret = _resolve(scale, d, interpret)
    sq, sk = q.shape[2], k.shape[2]
    bq, bk, ok = _resolve_blocks(sq, sk, block_q, block_k)
    if not _HAS_PALLAS or not ok:
        return attention_reference(q, k, v, causal, scale), None
    out, lse = _flash_fwd_pallas(q, k, v, causal, scale, bq, bk, interpret)
    return out, lse


def _flash_vjp_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    out, lse = _flash_fwd(q, k, v, causal, scale, block_q, block_k,
                          interpret)
    if lse is None:  # fallback path: vjp of the reference impl
        d = q.shape[-1]
        s, _ = _resolve(scale, d, interpret)
        _, ref_vjp = jax.vjp(
            lambda q_, k_, v_: attention_reference(q_, k_, v_, causal, s),
            q, k, v)
        return out, (None, ref_vjp)
    return out, ((q, k, v, out, lse), None)


def _flash_vjp_bwd(causal, scale, block_q, block_k, interpret, res, g):
    saved, ref_vjp = res
    if saved is None:
        return ref_vjp(g)
    q, k, v, out, lse = saved
    d = q.shape[-1]
    s, interp = _resolve(scale, d, interpret)
    sq, sk = q.shape[2], k.shape[2]
    # backward kernels hold ~3x the tiles of forward (q/k/v/do + two
    # accumulators); 1024-blocks overflow the 16MB scoped VMEM, so cap the
    # target at 512 and fit to a dividing block (a capped explicit block
    # may stop tiling evenly — e.g. 768 -> 512 with S=768 — so refit
    # rather than crash the blockwise fallback on a non-divisor)
    bq = _fit_block(sq, min(block_q or _default_block(sq), 512))
    bk = _fit_block(sk, min(block_k or _default_block(sk), 512))
    if _HAS_PALLAS and bq and bk:
        return _flash_bwd_pallas(q, k, v, out, lse, g, causal, s, bq, bk,
                                 interp)
    return _flash_bwd_blockwise(q, k, v, out, lse, g, causal, s,
                                _fit_block(sk, 512) or sk)


flash_attention.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


# ======================= 3. ring attention ===============================
#
# Two implementations, same math:
#   _ring_jnp    — einsum per hop (O(S_local^2) scores materialized);
#                  ground truth, and fallback when shards don't tile.
#   _ring_flash  — the Pallas flash kernel per hop + lse merge, with a
#                  second ring for the backward: kernel speed and O(block)
#                  memory on the long-context path itself. Per hop the
#                  K/V shard's origin decides the mask: src < my -> fully
#                  visible, src == my -> the causal diagonal, src > my ->
#                  skipped (zero contribution).
# `ring_attention` dispatches between them.


def _ring_flash_fwd_impl(q, k, v, axis_name, causal, scale, bq, bk, interp):
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]
    B, H, S, D = q.shape
    f32 = jnp.float32

    def hop(k_cur, v_cur, src):
        def full(_):
            o, l = _flash_fwd_pallas(q, k_cur, v_cur, False, scale, bq, bk,
                                     interp)
            return o.astype(f32), l

        def diag(_):
            o, l = _flash_fwd_pallas(q, k_cur, v_cur, True, scale, bq, bk,
                                     interp)
            return o.astype(f32), l

        def skip(_):
            return (jnp.zeros((B, H, S, D), f32),
                    jnp.full((B, H, S), _NEG_INF, f32))

        if not causal:
            return full(None)
        idx = jnp.where(src < my, 0, jnp.where(src == my, 1, 2))
        return lax.switch(idx, (full, diag, skip), None)

    def step(carry, step_i):
        m, z, num, k_cur, v_cur = carry
        src = (my - step_i) % n
        o_i, lse_i = hop(k_cur, v_cur, src)
        m_new = jnp.maximum(m, lse_i)
        corr = jnp.exp(m - m_new)
        w = jnp.exp(lse_i - m_new)
        z = z * corr + w
        num = num * corr[..., None] + w[..., None] * o_i
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return (m_new, z, num, k_nxt, v_nxt), None

    init = (jnp.full((B, H, S), _NEG_INF, f32),
            jnp.zeros((B, H, S), f32),
            jnp.zeros((B, H, S, D), f32), k, v)
    (m, z, num, _, _), _ = lax.scan(step, init, jnp.arange(n))
    z = jnp.maximum(z, 1e-20)
    out = (num / z[..., None]).astype(q.dtype)
    lse = m + jnp.log(z)
    return out, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _ring_flash(q, k, v, axis_name, causal, scale, bq, bk, interp):
    out, _ = _ring_flash_fwd_impl(q, k, v, axis_name, causal, scale, bq,
                                  bk, interp)
    return out


def _ring_flash_vjp_fwd(q, k, v, axis_name, causal, scale, bq, bk, interp):
    out, lse = _ring_flash_fwd_impl(q, k, v, axis_name, causal, scale, bq,
                                    bk, interp)
    return out, (q, k, v, out, lse)


def _ring_flash_vjp_bwd(axis_name, causal, scale, bq, bk, interp, res, g):
    q, k, v, out, lse = res
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]
    f32 = jnp.float32
    # backward tiles capped at 512 for VMEM, same as single-shard flash
    sq, sk = q.shape[2], k.shape[2]
    bqb = _fit_block(sq, min(bq, 512))
    bkb = _fit_block(sk, min(bk, 512))

    stats = _flash_bwd_stats(out, lse, g)  # loop-invariant across hops

    def hop(k_cur, v_cur, src):
        def run(causal_flag):
            def f(_):
                dq, dk, dv = _flash_bwd_pallas(q, k_cur, v_cur, out, lse,
                                               g, causal_flag, scale, bqb,
                                               bkb, interp, stats=stats)
                return dq.astype(f32), dk.astype(f32), dv.astype(f32)
            return f

        def skip(_):
            return (jnp.zeros(q.shape, f32), jnp.zeros(k.shape, f32),
                    jnp.zeros(v.shape, f32))

        if not causal:
            return run(False)(None)
        idx = jnp.where(src < my, 0, jnp.where(src == my, 1, 2))
        return lax.switch(idx, (run(False), run(True), skip), None)

    def step(carry, step_i):
        dq_acc, k_cur, v_cur, dk_cur, dv_cur = carry
        src = (my - step_i) % n
        dq_i, dk_i, dv_i = hop(k_cur, v_cur, src)
        dq_acc = dq_acc + dq_i
        # dk/dv accumulate onto the rotating shard so that after n hops
        # every contribution has ridden the ring home with its shard
        dk_cur = dk_cur + dk_i
        dv_cur = dv_cur + dv_i
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        dk_nxt = lax.ppermute(dk_cur, axis_name, perm)
        dv_nxt = lax.ppermute(dv_cur, axis_name, perm)
        return (dq_acc, k_nxt, v_nxt, dk_nxt, dv_nxt), None

    init = (jnp.zeros(q.shape, f32), k, v,
            jnp.zeros(k.shape, f32), jnp.zeros(v.shape, f32))
    (dq, _, _, dk, dv), _ = lax.scan(step, init, jnp.arange(n))
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_ring_flash.defvjp(_ring_flash_vjp_fwd, _ring_flash_vjp_bwd)


def ring_attention(q, k, v, axis_name: str, causal=False, scale=None):
    """Sequence-parallel attention INSIDE shard_map: q/k/v hold this
    device's sequence shard (B,H,S_local,D); the axis is the 'sp' mesh
    dimension. K/V shards rotate around the ring with lax.ppermute while
    each device accumulates online-softmax partials — peak memory is one
    shard, total traffic (n-1) shard-hops over ICI, and XLA overlaps each
    hop with the local block's matmuls.

    When the local shard tiles for the Pallas kernel, each hop runs the
    flash kernel (O(block) score memory, kernel speed); otherwise the
    jnp einsum path below is the fallback.
    """
    d = q.shape[-1]
    sq, sk = q.shape[2], k.shape[2]
    resolved_scale = scale if scale is not None else d ** -0.5
    bq = _fit_block(sq, _default_block(sq))
    bk = _fit_block(sk, _default_block(sk))
    # the backward ring has no blockwise fallback, so its capped tiles
    # must fit as well (e.g. S_local=2032: fwd fits 1016 but nothing in
    # [128,512] divides it)
    bwd_ok = _fit_block(sq, min(bq or 0, 512)) and \
        _fit_block(sk, min(bk or 0, 512))
    if _HAS_PALLAS and bq and bk and bwd_ok:
        _, interp = _resolve(resolved_scale, d, None)
        return _ring_flash(q, k, v, axis_name, causal, resolved_scale,
                           bq, bk, interp)
    return _ring_jnp(q, k, v, axis_name, causal, scale)


def _ring_jnp(q, k, v, axis_name: str, causal=False, scale=None):
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    d = q.shape[-1]
    s_local = q.shape[2]
    scale = scale if scale is not None else d ** -0.5
    perm = [(i, (i + 1) % n) for i in range(n)]

    qs = q.astype(jnp.float32) * scale
    m = jnp.full(q.shape[:3] + (1,), _NEG_INF, jnp.float32)
    l = jnp.zeros(q.shape[:3] + (1,), jnp.float32)
    acc = jnp.zeros(qs.shape, jnp.float32)

    def step(carry, step_i):
        m, l, acc, k_cur, v_cur = carry
        src = (my - step_i) % n  # which global shard k_cur came from
        s = jnp.einsum("bhqd,bhkd->bhqk", qs, k_cur.astype(jnp.float32))
        if causal:
            s = s + _causal_mask(s_local, s_local, my * s_local,
                                 src * s_local)[None, None]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * corr + jnp.einsum(
            "bhqk,bhkd->bhqd", p, v_cur.astype(jnp.float32))
        # rotate K/V to the next device (no-op cost on the last step's
        # result; XLA prunes the final unused permute's consumer)
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return (m_new, l_new, acc_new, k_nxt, v_nxt), None

    (m, l, acc, _, _), _ = lax.scan(step, (m, l, acc, k, v), jnp.arange(n))
    # fully-masked rows (causal, early shards) have l == 0; guard division
    l = jnp.maximum(l, 1e-20)
    return (acc / l).astype(q.dtype)


# ======================= 4. int4 nibble packing ==========================
#
# int4 KV quantization packs TWO 4-bit values per byte along the lane
# (feature) dimension, split-half layout: byte j of a packed row holds
# lane j in its LOW nibble and lane j + L/2 in its HIGH nibble, so the
# unpack is a concat of two sign-extended halves — no strided interleave,
# and a per-token cache-row write stays a contiguous byte-aligned slice
# (packing along the token dim would force read-modify-write of bytes
# shared between positions). Values are symmetric int4 in [-7, 7] with
# the same per-(head, position) scale layout the int8 path uses (scale
# basis max|kv| / 7 instead of / 127) — the scale algebra downstream is
# IDENTICAL, only the byte stream halves again.

def nibble_pack(q):
    """(..., L) int values in [-8, 7] -> (..., L/2) uint8, split-half
    layout (low nibble = lane j, high nibble = lane j + L/2)."""
    L = q.shape[-1]
    assert L % 2 == 0, f"nibble_pack needs an even last dim, got {L}"
    u = q.astype(jnp.uint8)
    lo = u[..., : L // 2] & 0xF
    hi = u[..., L // 2:] & 0xF
    return (hi << 4) | lo


def nibble_unpack(p, dtype=jnp.float32):
    """(..., L/2) uint8 -> (..., L) `dtype`, inverting nibble_pack.
    Arithmetic runs in int32 (sign extension via the 0x8 test) so the
    same expression lowers in Pallas/Mosaic and under plain XLA."""
    x = p.astype(jnp.int32)
    lo = x & 0xF
    hi = (x >> 4) & 0xF
    lo = lo - ((lo & 0x8) << 1)
    hi = hi - ((hi & 0x8) << 1)
    return jnp.concatenate([lo, hi], axis=-1).astype(dtype)


def _kv_dequant(blk, qdtype):
    """Pool/cache block -> matmul operand in the query dtype: int4
    (uint8 packed) unpacks nibbles, int8 casts, float passes through."""
    if blk.dtype == jnp.uint8:
        return nibble_unpack(blk, qdtype)
    if blk.dtype == jnp.int8:
        return blk.astype(qdtype)
    return blk


# ======================= 5. paged decode attention =======================
#
# The serving engine's ragged decode path (singa_tpu.engine): each active
# sequence owns a host-assigned list of fixed-size KV-cache PAGES in a
# shared pool, so a 32-token request stops reserving max-length HBM. The
# attention here is the decode-side flash pattern — one packed query row
# block per sequence, online softmax over its pages — with the page
# table driving WHICH pool rows stream through VMEM (vLLM/PagedAttention
# moved to Pallas scalar prefetch: the BlockSpec index map reads the
# prefetched page table, so only the sequence's own pages are DMA'd).
#
# Two tiers, same math, mirroring flash_attention:
#   paged_attention_reference — gather + masked softmax in jnp; ground
#       truth, and the dispatch default off-TPU (a decode step is tiny;
#       unrolling an interpret-mode grid into every scan step is not).
#   _paged_fwd_pallas — PrefetchScalarGridSpec kernel, grid
#       (seqs, packed-kv-heads, pages): K/V pages stream one at a time,
#       pages at or beyond a sequence's length are neither computed nor
#       DMA'd (the index map clamps to the last needed page, so the
#       block index doesn't change and Pallas elides the copy).
#
# Layout matches the serving cache convention: queries arrive HEAD-PACKED
# block-diagonal (N, Hp, Q, P*D) with Q = P*G rows (serving.py builds
# them via _DecodeCore._pack_q), pools are (n_pages, Hp, page_size, P*D).
# int8 KV is preserved: per-(head, position) scale pools ride along and
# fold into scores/weights exactly as the dense token_step does.

def _paged_factors(sc, groups, rows, q_tokens=1):
    """(T?, P) per-position scales -> (rows, T?) row factors for packed
    block-diagonal queries: row q = c*groups + g reads lane block c.
    With `q_tokens` > 1 (the speculative verify step) the row layout is
    (q_tokens, P, groups) — every token's P*G block reads the same
    per-position factors, so the block is tiled along the row dim.
    Rows beyond q_tokens*P*groups (query padding) get factor 1."""
    f = jnp.repeat(sc.swapaxes(-1, -2), groups, axis=-2)  # (P*G, T)
    if q_tokens > 1:
        f = jnp.concatenate([f] * q_tokens, axis=-2)
    pg = sc.shape[-1] * groups * q_tokens
    if rows > pg:
        pad = jnp.ones(f.shape[:-2] + (rows - pg, f.shape[-1]), f.dtype)
        f = jnp.concatenate([f, pad], axis=-2)
    return f


def _row_limits(lengths, Q, rows_per_token, q_tokens):
    """(N,) final lengths -> (N, Q) per-query-row KV limits. Query rows
    are laid out (q_tokens, P, G): token ti's rows attend positions
    < lengths - (q_tokens - 1 - ti) — the causal ladder of the
    multi-token verify step. q_tokens == 1 is the plain decode case
    (every row sees `lengths` positions). Padding rows (>= q_tokens *
    rows_per_token) inherit the LAST token's limit (outputs
    discarded)."""
    ti = jnp.minimum(jnp.arange(Q) // rows_per_token, q_tokens - 1)
    return lengths[:, None] - (q_tokens - 1 - ti)[None, :]


def paged_attention_reference(q, k_pool, v_pool, page_table, lengths,
                              page_size, scale=1.0, k_scales=None,
                              v_scales=None, groups=1, q_tokens=1):
    """Ground-truth paged decode attention.

    q:          (N, Hp, Q, PD) packed block-diagonal queries
                (Q = q_tokens * P * G; q_tokens > 1 is the speculative
                verify step — token ti's rows attend q_tokens-1-ti
                fewer positions, the causal ladder)
    k_pool/v_pool: (n_pages, Hp, page_size, PD) shared page pools
                (int8 when k_scales/v_scales are given; packed uint8
                (n_pages, Hp, page_size, PD/2) for int4 KV)
    page_table: (N, M) int32 — page ids per sequence, row-major in time
    lengths:    (N,) int32 — valid KV positions per sequence (>= 1),
                counted at the LAST query token under q_tokens > 1
    k_scales/v_scales: (n_pages, Hp, page_size, P) fp32 (quantized KV)

    Returns (N, Hp, Q, PD). The math is the dense token_step's masked
    softmax over the gathered pages — gathers materialize a copy, which
    is why the TPU path streams pages in the kernel instead."""
    N, Hp, Q, PD = q.shape
    M = page_table.shape[1]
    T = M * page_size

    def gather(pool):
        g = pool[page_table]                   # (N, M, Hp, ps, PD/P)
        g = jnp.moveaxis(g, 2, 1)              # (N, Hp, M, ps, ·)
        return g.reshape(N, Hp, T, g.shape[-1])

    kf = _kv_dequant(gather(k_pool), q.dtype)
    vf = _kv_dequant(gather(v_pool), q.dtype)
    s = jnp.einsum("nhqd,nhtd->nhqt", q, kf) * scale
    if k_scales is not None:
        s = s * _paged_factors(gather(k_scales), groups, Q, q_tokens)
    limits = _row_limits(lengths, Q, Q // max(q_tokens, 1), q_tokens)
    valid = (lax.broadcasted_iota(jnp.int32, (1, 1, 1, T), 3)
             < limits[:, None, :, None])
    a = jax.nn.softmax(jnp.where(valid, s, -jnp.inf), axis=-1)
    if v_scales is not None:
        a = a * _paged_factors(gather(v_scales), groups, Q, q_tokens)
    return jnp.einsum("nhqt,nhtd->nhqd", a.astype(q.dtype),
                      vf).astype(q.dtype)


def _paged_fwd_kernel(pt_ref, len_ref, q_ref, k_ref, v_ref, *rest,
                      nM, page_size, groups, kvq, q_tokens,
                      rows_per_token):
    """Grid (N, Hp, pages): stream one sequence's pages through VMEM and
    run the online softmax. Pages past the sequence length are gated
    (compute) and their DMA elided (index map re-addresses the last
    needed page). int8 K/V cast in-kernel; int4 K/V arrive as packed
    uint8 (ps, PD/2) blocks and UNPACK in-kernel (nibble_unpack in
    int32 arithmetic) — the HBM stream is the packed bytes, the MXU
    sees the query dtype. With q_tokens > 1 (speculative verify) query
    rows are laid out (q_tokens, P, G) and token ti's rows mask
    positions >= len - (q_tokens-1-ti): the causal ladder. CONTRACT:
    fully sequential grid — the scratch state persists across the page
    dimension."""
    if kvq:
        ks_ref, vs_ref, o_ref, acc_ref, m_ref, l_ref = rest
    else:
        o_ref, acc_ref, m_ref, l_ref = rest
        ks_ref = vs_ref = None
    n = pl.program_id(0)
    pg = pl.program_id(2)

    @pl.when(pg == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    ln = len_ref[n]
    needed = pg * page_size < ln

    def _update():
        # q arrives PRE-SCALED (the wrapper folds the softmax scale in,
        # like flash); quantized K/V dequant in-kernel to the query
        # dtype for native MXU dots, scales fold in exactly as the
        # dense quantized token_step does
        q = q_ref[0, 0]                         # (Qp, PD)
        k_blk = _kv_dequant(k_ref[0, 0], q.dtype)   # (ps, PD)
        v_blk = _kv_dequant(v_ref[0, 0], q.dtype)
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32)
        if kvq:
            s = s * _paged_factors(ks_ref[0, 0], groups, s.shape[0],
                                   q_tokens)
        pos = pg * page_size + lax.broadcasted_iota(
            jnp.int32, (1, page_size), 1)
        if q_tokens > 1:
            ti = jnp.minimum(
                lax.broadcasted_iota(jnp.int32, (s.shape[0], 1), 0)
                // rows_per_token, q_tokens - 1)
            s = jnp.where(pos < ln - (q_tokens - 1 - ti), s, _NEG_INF)
        else:
            s = jnp.where(pos < ln, s, _NEG_INF)
        m_prev = m_ref[...][:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        l_new = l_ref[...][:, :1] * corr \
            + jnp.sum(p, axis=-1, keepdims=True)
        if kvq:
            p = p * _paged_factors(vs_ref[0, 0], groups, p.shape[0],
                                   q_tokens)
        acc_ref[...] = acc_ref[...] * corr + jnp.dot(
            p.astype(v_blk.dtype), v_blk,
            preferred_element_type=jnp.float32)
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    pl.when(needed)(_update)

    @pl.when(pg == nM - 1)
    def _finish():
        l = jnp.maximum(l_ref[...][:, :1], 1e-20)
        o_ref[0, 0] = (acc_ref[...] / l).astype(o_ref.dtype)


def _paged_fwd_pallas(q, k_pool, v_pool, page_table, lengths, page_size,
                      scale, k_scales, v_scales, groups, interpret,
                      q_tokens=1):
    N, Hp, Q, PD = q.shape
    M = page_table.shape[1]
    ps = page_size
    kvq = None
    if k_scales is not None:
        kvq = "int4" if k_pool.dtype == jnp.uint8 else "int8"
    PDk = k_pool.shape[-1]          # PD, or PD/2 for packed int4
    # pad query rows to the 8-sublane alignment; extra rows are zeros
    # (their softmax output is garbage over a zero query — discarded)
    Qp = max(8, Q + (-Q) % 8)
    qf = (q * scale).astype(q.dtype)
    if Qp != Q:
        qf = jnp.concatenate(
            [qf, jnp.zeros((N, Hp, Qp - Q, PD), qf.dtype)], axis=2)
    lengths = jnp.maximum(lengths.astype(jnp.int32), 1)
    pt = page_table.astype(jnp.int32)

    def page_map(n, hp, pg, pt_ref, len_ref):
        # clamp to the last needed page: fully-masked steps re-address
        # it, so their DMA is elided (the block index doesn't change)
        last = jnp.minimum((len_ref[n] - 1) // ps, M - 1)
        return (pt_ref[n, jnp.minimum(pg, last)], hp, 0, 0)

    def q_map(n, hp, pg, pt_ref, len_ref):
        return (n, hp, 0, 0)

    in_specs = [
        pl.BlockSpec((1, 1, Qp, PD), q_map),
        pl.BlockSpec((1, 1, ps, PDk), page_map),
        pl.BlockSpec((1, 1, ps, PDk), page_map),
    ]
    operands = [qf, k_pool, v_pool]
    if kvq:
        in_specs += [pl.BlockSpec((1, 1, ps, k_scales.shape[-1]),
                                  page_map),
                     pl.BlockSpec((1, 1, ps, v_scales.shape[-1]),
                                  page_map)]
        operands += [k_scales, v_scales]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(N, Hp, M),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, Qp, PD), q_map),
        scratch_shapes=[
            pltpu.VMEM((Qp, PD), jnp.float32),
            pltpu.VMEM((Qp, _STAT_LANES), jnp.float32),
            pltpu.VMEM((Qp, _STAT_LANES), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_paged_fwd_kernel, nM=M, page_size=ps,
                          groups=groups, kvq=kvq, q_tokens=q_tokens,
                          rows_per_token=Q // max(q_tokens, 1)),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((N, Hp, Qp, PD), q.dtype),
        interpret=interpret,
    )(pt, lengths, *operands)
    return out[:, :, :Q, :]


def paged_attention(q, k_pool, v_pool, page_table, lengths, page_size,
                    scale=1.0, k_scales=None, v_scales=None, groups=1,
                    use_kernel=None, q_tokens=1):
    """Paged decode attention: dispatch between the Pallas page-streaming
    kernel and the gather-based reference (see paged_attention_reference
    for shapes — int8 and packed-int4 pools dequantize in-kernel;
    q_tokens > 1 runs the speculative verify's causal ladder over
    (q_tokens, P, G)-laid-out query rows). `use_kernel=None` picks the
    kernel only on a real TPU backend — off-TPU the kernel would run in
    interpret mode, unrolling the whole (N, Hp, pages) grid into every
    traced decode step; `use_kernel=True` forces it (interpret off-TPU,
    how the agreement test exercises the kernel path), False forces the
    reference."""
    N, Hp, Q, PD = q.shape
    ps = int(page_size)
    on_tpu = jax.default_backend() == "tpu"
    # lane/sublane alignment gates only the COMPILED path; interpret
    # mode (the off-TPU agreement tests, incl. int4's PD/2-lane packed
    # pools at small test dims) has no tiling constraint
    aligned = (ps % 8 == 0 and PD % 128 == 0
               and k_pool.shape[-1] % 128 == 0)
    if use_kernel is None:
        use_kernel = on_tpu and aligned
    if not use_kernel or not _HAS_PALLAS or (on_tpu and not aligned):
        return paged_attention_reference(
            q, k_pool, v_pool, page_table, lengths, ps, scale,
            k_scales, v_scales, groups, q_tokens)
    interpret = not on_tpu
    return _paged_fwd_pallas(q, k_pool, v_pool, page_table, lengths, ps,
                             scale, k_scales, v_scales, groups, interpret,
                             q_tokens)


# ======================= 6. dense flash-decode ===========================
#
# The dense serving path's decode attention (serving._DecodeCore
# token_step / verify_step): one packed block-diagonal query row block
# per sequence against a CONTIGUOUS (N, Hp, T, PD) head-packed cache,
# masked to each sequence's live length. Same two-tier contract as
# paged_attention — `flash_decode_reference` is the jnp ground truth
# (and the off-TPU dispatch default; a decode step is tiny, an
# interpret-mode grid unrolled into every scan step is not), the Pallas
# kernel streams T blocks through VMEM with the online softmax, masked
# blocks' DMA elided via a scalar-prefetched length clamp. Quantized
# caches (int8, packed-nibble int4) dequantize IN-KERNEL: HBM streams
# the quantized bytes — the whole point of the quantization — and the
# MXU sees the query dtype. q_tokens > 1 runs the speculative verify
# ladder (token ti's rows attend q_tokens-1-ti fewer positions).

def flash_decode_reference(q, K, V, lengths, scale=1.0, k_scales=None,
                           v_scales=None, groups=1, q_tokens=1):
    """Ground-truth dense decode attention.

    q:        (N, Hp, Q, PD) packed block-diagonal queries
              (Q = q_tokens * P * G)
    K/V:      (N, Hp, T, PD) head-packed caches (float or int8), or
              packed uint8 (N, Hp, T, PD/2) for int4 KV
    lengths:  (N,) int32 — live positions per sequence, counted at the
              LAST query token under q_tokens > 1
    k_scales/v_scales: (N, Hp, T, P) fp32 (quantized KV only)

    Returns (N, Hp, Q, PD) — the dense token_step's masked softmax
    with the quantization-scale folding of the int8/int4 cache modes."""
    N, Hp, Q, PD = q.shape
    T = K.shape[2]
    kf = _kv_dequant(K, q.dtype)
    vf = _kv_dequant(V, q.dtype)
    s = jnp.einsum("nhqd,nhtd->nhqt", q, kf) * scale
    if k_scales is not None:
        s = s * _paged_factors(k_scales, groups, Q, q_tokens)
    limits = _row_limits(lengths, Q, Q // max(q_tokens, 1), q_tokens)
    valid = (lax.broadcasted_iota(jnp.int32, (1, 1, 1, T), 3)
             < limits[:, None, :, None])
    a = jax.nn.softmax(jnp.where(valid, s, -jnp.inf), axis=-1)
    if v_scales is not None:
        a = a * _paged_factors(v_scales, groups, Q, q_tokens)
    return jnp.einsum("nhqt,nhtd->nhqd", a.astype(q.dtype),
                      vf).astype(q.dtype)


def _flash_decode_kernel(len_ref, q_ref, k_ref, v_ref, *rest,
                         nT, block_t, groups, kvq, q_tokens,
                         rows_per_token):
    """Grid (N, Hp, t_blocks): stream one sequence's cache blocks
    through VMEM with the online softmax; blocks past the live length
    are gated (compute) and their DMA elided (index map clamps to the
    last needed block). Same contract as the paged kernel: fully
    sequential grid, scratch persists across the t dimension."""
    if kvq:
        ks_ref, vs_ref, o_ref, acc_ref, m_ref, l_ref = rest
    else:
        o_ref, acc_ref, m_ref, l_ref = rest
        ks_ref = vs_ref = None
    n = pl.program_id(0)
    tb = pl.program_id(2)

    @pl.when(tb == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    ln = len_ref[n]
    needed = tb * block_t < ln

    def _update():
        q = q_ref[0, 0]                              # (Qp, PD), scaled
        k_blk = _kv_dequant(k_ref[0, 0], q.dtype)    # (bt, PD)
        v_blk = _kv_dequant(v_ref[0, 0], q.dtype)
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32)
        if kvq:
            s = s * _paged_factors(ks_ref[0, 0], groups, s.shape[0],
                                   q_tokens)
        pos = tb * block_t + lax.broadcasted_iota(
            jnp.int32, (1, block_t), 1)
        if q_tokens > 1:
            ti = jnp.minimum(
                lax.broadcasted_iota(jnp.int32, (s.shape[0], 1), 0)
                // rows_per_token, q_tokens - 1)
            s = jnp.where(pos < ln - (q_tokens - 1 - ti), s, _NEG_INF)
        else:
            s = jnp.where(pos < ln, s, _NEG_INF)
        m_prev = m_ref[...][:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        l_new = l_ref[...][:, :1] * corr \
            + jnp.sum(p, axis=-1, keepdims=True)
        if kvq:
            p = p * _paged_factors(vs_ref[0, 0], groups, p.shape[0],
                                   q_tokens)
        acc_ref[...] = acc_ref[...] * corr + jnp.dot(
            p.astype(v_blk.dtype), v_blk,
            preferred_element_type=jnp.float32)
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    pl.when(needed)(_update)

    @pl.when(tb == nT - 1)
    def _finish():
        l = jnp.maximum(l_ref[...][:, :1], 1e-20)
        o_ref[0, 0] = (acc_ref[...] / l).astype(o_ref.dtype)


def _flash_decode_pallas(q, K, V, lengths, scale, k_scales, v_scales,
                         groups, interpret, q_tokens, block_t):
    N, Hp, Q, PD = q.shape
    T = K.shape[2]
    bt = block_t
    nT = T // bt
    kvq = None
    if k_scales is not None:
        kvq = "int4" if K.dtype == jnp.uint8 else "int8"
    PDk = K.shape[-1]
    Qp = max(8, Q + (-Q) % 8)
    qf = (q * scale).astype(q.dtype)
    if Qp != Q:
        qf = jnp.concatenate(
            [qf, jnp.zeros((N, Hp, Qp - Q, PD), qf.dtype)], axis=2)
    lengths = jnp.maximum(lengths.astype(jnp.int32), 1)

    def t_map(n, hp, tb, len_ref):
        # clamp to the last needed block so masked steps' DMA elides
        last = jnp.minimum((len_ref[n] - 1) // bt, nT - 1)
        return (n, hp, jnp.minimum(tb, last), 0)

    def q_map(n, hp, tb, len_ref):
        return (n, hp, 0, 0)

    in_specs = [
        pl.BlockSpec((1, 1, Qp, PD), q_map),
        pl.BlockSpec((1, 1, bt, PDk), t_map),
        pl.BlockSpec((1, 1, bt, PDk), t_map),
    ]
    operands = [qf, K, V]
    if kvq:
        in_specs += [pl.BlockSpec((1, 1, bt, k_scales.shape[-1]), t_map),
                     pl.BlockSpec((1, 1, bt, v_scales.shape[-1]), t_map)]
        operands += [k_scales, v_scales]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(N, Hp, nT),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, Qp, PD), q_map),
        scratch_shapes=[
            pltpu.VMEM((Qp, PD), jnp.float32),
            pltpu.VMEM((Qp, _STAT_LANES), jnp.float32),
            pltpu.VMEM((Qp, _STAT_LANES), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_flash_decode_kernel, nT=nT, block_t=bt,
                          groups=groups, kvq=kvq, q_tokens=q_tokens,
                          rows_per_token=Q // max(q_tokens, 1)),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((N, Hp, Qp, PD), q.dtype),
        interpret=interpret,
    )(lengths, *operands)
    return out[:, :, :Q, :]


def flash_decode(q, K, V, lengths, scale=1.0, k_scales=None,
                 v_scales=None, groups=1, use_kernel=None, q_tokens=1,
                 block_t=None):
    """Dense decode attention: dispatch between the Pallas
    block-streaming kernel and the jnp reference (see
    flash_decode_reference for shapes). `use_kernel=None` picks the
    kernel only on a real TPU backend with tiling alignment;
    `use_kernel=True` forces it (interpret off-TPU — the agreement
    tests), False forces the reference."""
    N, Hp, Q, PD = q.shape
    T = K.shape[2]
    on_tpu = jax.default_backend() == "tpu"
    bt = block_t if block_t is not None else _fit_block(
        T, min(256, T), floor=8)
    aligned = (bt is not None and PD % 128 == 0
               and K.shape[-1] % 128 == 0 and bt % 8 == 0)
    if use_kernel is None:
        use_kernel = on_tpu and aligned
    if not use_kernel or not _HAS_PALLAS or bt is None \
            or (on_tpu and not aligned):
        return flash_decode_reference(q, K, V, lengths, scale, k_scales,
                                      v_scales, groups, q_tokens)
    return _flash_decode_pallas(q, K, V, lengths, scale, k_scales,
                                v_scales, groups, not on_tpu, q_tokens,
                                bt)


def ring_attention_sharded(q, k, v, mesh, axis_name="sp", causal=False):
    """Convenience wrapper: shard (B,H,S,D) arrays over `axis_name` on the
    seq dim and run ring_attention under shard_map."""
    from jax.sharding import PartitionSpec as P
    spec = P(None, None, axis_name, None)

    @functools.partial(jax.shard_map, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, check_vma=False)
    def run(q_, k_, v_):
        return ring_attention(q_, k_, v_, axis_name, causal)

    return run(q, k, v)
