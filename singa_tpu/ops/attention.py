"""Attention ops: fused flash attention (Pallas) + ring attention (sequence
parallel over a mesh axis).

No counterpart exists in the reference — it has no attention op at all
(SURVEY.md §2.3: transformers enter only via ONNX import) — but long-context
is first-class here. Layout is (batch, heads, seq, head_dim) throughout.

Three tiers, same math:
  1. `attention_reference`  — jnp, O(S^2) memory; ground truth for tests.
  2. `flash_attention`      — Pallas online-softmax kernel, O(S) memory,
                              custom_vjp with blockwise recompute backward.
  3. `ring_attention`       — flash over sequence shards on a mesh axis;
                              K/V blocks rotate via lax.ppermute so each
                              ICI hop overlaps with the local block matmul
                              (the jax-native form of the RDMA ring pattern
                              in /opt/skills/guides/pallas_guide.md §18).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

_NEG_INF = -1e30


def _causal_mask(sq, sk, q_off=0, k_off=0, dtype=jnp.float32):
    q_pos = q_off + lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
    k_pos = k_off + lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
    return jnp.where(k_pos > q_pos, _NEG_INF, 0.0).astype(dtype)


# ======================= 1. reference ====================================

def attention_reference(q, k, v, causal=False, scale=None):
    """q,k,v: (B, H, S, D). Returns (B, H, Sq, D)."""
    d = q.shape[-1]
    scale = scale if scale is not None else d ** -0.5
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        s = s + _causal_mask(q.shape[2], k.shape[2], dtype=s.dtype)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


# ======================= 2. flash attention ==============================
# Online-softmax over K blocks; the kernel keeps one (Bq, D) accumulator,
# running row-max m and row-sum l in VMEM scratch. Backward recomputes
# blockwise (no S matrix ever materialized).

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *,
                      block_k, seq_k, causal, scale, block_q):
    """Grid: (batch*heads, q_blocks). Refs are (1, block_q, D) for q/o and
    (1, seq_k, D) for k/v (whole K/V row per head in VMEM)."""
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale           # (Bq, D)
    bq, d = q.shape
    m = jnp.full((bq, 1), _NEG_INF, jnp.float32)
    l = jnp.zeros((bq, 1), jnp.float32)
    acc = jnp.zeros((bq, d), jnp.float32)

    num_kb = seq_k // block_k

    def body(kb, carry):
        m, l, acc = carry
        k_blk = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32)
        if causal:
            s = s + _causal_mask(bq, block_k, q_off=qi * block_q,
                                 k_off=kb * block_k)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * corr + jnp.dot(p, v_blk,
                                   preferred_element_type=jnp.float32)
        return m_new, l, acc

    if causal:
        # skip K blocks strictly above the diagonal
        last = (qi + 1) * block_q  # first k index NOT needed
        num_needed = pl.cdiv(last, block_k)
        m, l, acc = lax.fori_loop(0, num_needed, body, (m, l, acc))
    else:
        m, l, acc = lax.fori_loop(0, num_kb, body, (m, l, acc))

    o_ref[0] = (acc / l).astype(o_ref.dtype)
    lse_ref[0] = (m + jnp.log(l))[:, 0]


try:  # import here so CPU-only environments still import the module
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PALLAS = True
except ImportError:  # pragma: no cover
    _HAS_PALLAS = False


def _flash_fwd_pallas(q, k, v, causal, scale, block_q, block_k, interpret):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    bh = b * h
    qf = q.reshape(bh, sq, d)
    kf = k.reshape(bh, sk, d)
    vf = v.reshape(bh, sk, d)
    grid = (bh, sq // block_q)
    kernel = functools.partial(
        _flash_fwd_kernel, block_k=block_k, seq_k=sk, causal=causal,
        scale=scale, block_q=block_q)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, sk, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, sk, d), lambda i, j: (i, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, block_q), lambda i, j: (i, j)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, sq), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(b, h, sq, d), lse.reshape(b, h, sq)


def _flash_bwd_blockwise(q, k, v, o, lse, do, causal, scale, block_k):
    """Recompute-based backward, scanned over K blocks (O(S) memory)."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    qs = q.astype(jnp.float32) * scale
    do_ = do.astype(jnp.float32)
    # delta = rowsum(do * o)  (standard flash-2 backward term)
    delta = jnp.sum(do_ * o.astype(jnp.float32), axis=-1)  # (B,H,Sq)

    nkb = sk // block_k
    kb_idx = jnp.arange(nkb)

    def per_kblock(kb):
        k_blk = lax.dynamic_slice_in_dim(k, kb * block_k, block_k, axis=2)
        v_blk = lax.dynamic_slice_in_dim(v, kb * block_k, block_k, axis=2)
        s = jnp.einsum("bhqd,bhkd->bhqk", qs, k_blk.astype(jnp.float32))
        if causal:
            s = s + _causal_mask(sq, block_k, 0, kb * block_k)[None, None]
        p = jnp.exp(s - lse[..., None])                    # (B,H,Sq,Bk)
        dv = jnp.einsum("bhqk,bhqd->bhkd", p, do_)
        dp = jnp.einsum("bhqd,bhkd->bhqk", do_, v_blk.astype(jnp.float32))
        ds = p * (dp - delta[..., None])
        dk = jnp.einsum("bhqk,bhqd->bhkd", ds, qs) * 1.0
        dq_part = jnp.einsum("bhqk,bhkd->bhqd", ds,
                             k_blk.astype(jnp.float32))
        return dq_part, dk, dv

    def scan_body(dq_acc, kb):
        dq_part, dk, dv = per_kblock(kb)
        return dq_acc + dq_part, (dk, dv)

    dq, (dks, dvs) = lax.scan(scan_body,
                              jnp.zeros(q.shape, jnp.float32), kb_idx)
    dk = jnp.moveaxis(dks, 0, 2).reshape(b, h, sk, d)
    dv = jnp.moveaxis(dvs, 0, 2).reshape(b, h, sk, d)
    return (dq * scale).astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(q, k, v, causal=False, scale=None,
                    block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K,
                    interpret=None):
    """Fused attention; q,k,v (B,H,S,D). Falls back to the reference path
    when shapes don't tile (S % block != 0) or Pallas is unavailable."""
    out, _ = _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret)
    return out


def _resolve(scale, d, interpret):
    scale = scale if scale is not None else d ** -0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return scale, interpret


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    d = q.shape[-1]
    scale, interpret = _resolve(scale, d, interpret)
    sq, sk = q.shape[2], k.shape[2]
    # shrink blocks only to hardware-aligned sizes; anything that still
    # doesn't tile falls back to the reference path
    block_q = min(block_q, sq) if sq % min(block_q, sq) == 0 \
        and min(block_q, sq) % 8 == 0 else block_q
    block_k = min(block_k, sk) if sk % min(block_k, sk) == 0 \
        and min(block_k, sk) % 8 == 0 else block_k
    if (not _HAS_PALLAS or sq % block_q or sk % block_k):
        out = attention_reference(q, k, v, causal, scale)
        lse = None
    else:
        out, lse = _flash_fwd_pallas(q, k, v, causal, scale, block_q,
                                     block_k, interpret)
    return out, lse


def _flash_vjp_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    out, lse = _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret)
    if lse is None:  # fallback path: vjp of the reference impl
        d = q.shape[-1]
        s, _ = _resolve(scale, d, interpret)
        _, ref_vjp = jax.vjp(
            lambda q_, k_, v_: attention_reference(q_, k_, v_, causal, s),
            q, k, v)
        return out, (None, ref_vjp)
    return out, ((q, k, v, out, lse), None)


def _flash_vjp_bwd(causal, scale, block_q, block_k, interpret, res, g):
    saved, ref_vjp = res
    if saved is None:
        return ref_vjp(g)
    q, k, v, out, lse = saved
    d = q.shape[-1]
    s, _ = _resolve(scale, d, interpret)
    bk = min(block_k, k.shape[2])
    return _flash_bwd_blockwise(q, k, v, out, lse, g, causal, s, bk)


flash_attention.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


# ======================= 3. ring attention ===============================

def ring_attention(q, k, v, axis_name: str, causal=False, scale=None):
    """Sequence-parallel attention INSIDE shard_map: q/k/v hold this
    device's sequence shard (B,H,S_local,D); the axis is the 'sp' mesh
    dimension. K/V shards rotate around the ring with lax.ppermute while
    each device accumulates online-softmax partials — peak memory is one
    shard, total traffic (n-1) shard-hops over ICI, and XLA overlaps each
    hop with the local block's matmuls.
    """
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    d = q.shape[-1]
    s_local = q.shape[2]
    scale = scale if scale is not None else d ** -0.5
    perm = [(i, (i + 1) % n) for i in range(n)]

    qs = q.astype(jnp.float32) * scale
    m = jnp.full(q.shape[:3] + (1,), _NEG_INF, jnp.float32)
    l = jnp.zeros(q.shape[:3] + (1,), jnp.float32)
    acc = jnp.zeros(qs.shape, jnp.float32)

    def step(carry, step_i):
        m, l, acc, k_cur, v_cur = carry
        src = (my - step_i) % n  # which global shard k_cur came from
        s = jnp.einsum("bhqd,bhkd->bhqk", qs, k_cur.astype(jnp.float32))
        if causal:
            s = s + _causal_mask(s_local, s_local, my * s_local,
                                 src * s_local)[None, None]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * corr + jnp.einsum(
            "bhqk,bhkd->bhqd", p, v_cur.astype(jnp.float32))
        # rotate K/V to the next device (no-op cost on the last step's
        # result; XLA prunes the final unused permute's consumer)
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return (m_new, l_new, acc_new, k_nxt, v_nxt), None

    (m, l, acc, _, _), _ = lax.scan(step, (m, l, acc, k, v), jnp.arange(n))
    # fully-masked rows (causal, early shards) have l == 0; guard division
    l = jnp.maximum(l, 1e-20)
    return (acc / l).astype(q.dtype)


def ring_attention_sharded(q, k, v, mesh, axis_name="sp", causal=False):
    """Convenience wrapper: shard (B,H,S,D) arrays over `axis_name` on the
    seq dim and run ring_attention under shard_map."""
    from jax.sharding import PartitionSpec as P
    spec = P(None, None, axis_name, None)

    @functools.partial(jax.shard_map, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, check_vma=False)
    def run(q_, k_, v_):
        return ring_attention(q_, k_, v_, axis_name, causal)

    return run(q, k, v)
