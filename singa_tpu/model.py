"""Model API with trace-once graph buffering.

Reference parity: python/singa/model.py — `ModelMeta.buffer_operation`
(model.py:41-100) makes the *first* `train_one_batch` call trace all ops
into the C++ `Graph`, then replays `dev.RunGraph(sequential)` every
iteration; `compile()` (:156-184) runs a dummy forward to shape-infer and
init params; `save_states/load_states` use zip(npz + json) (:244-354).

TPU-native redesign: "trace once, replay" IS `jax.jit`: the first call
builds a functional step (model states + optimizer states threaded through,
buffers donated so params update in place), compiles it with XLA, and every
later call replays the executable with zero Python op dispatch. Distributed
training shard_maps the same step over a mesh so DistOpt's `lax.psum` calls
bind to the data axis — the XLA analog of submitting NCCL ops as graph
nodes (communicator.cc:175-186).
"""

from __future__ import annotations

import io
import json
import os
import time
import zipfile

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from . import _compat  # noqa: F401  (installs jax.shard_map on old jax)
from . import autograd
from . import goodput
from . import health
from . import introspect
from . import memory
from . import observe
from . import watchdog
from .layer import Layer, LayerMeta
from .tensor import Tensor


_AOT_MISS = introspect._AOT_MISS  # shared "no cache entry yet" sentinel


def _flatten_out(out):
    """Flatten nested tuples/lists/dicts of Tensors -> (leaves, rebuild)."""
    leaves = []

    def build_template(o):
        if isinstance(o, Tensor):
            leaves.append(o)
            return ("T", len(leaves) - 1)
        if isinstance(o, (tuple, list)):
            return ("L", type(o).__name__, [build_template(v) for v in o])
        if isinstance(o, dict):
            return ("D", {k: build_template(v) for k, v in o.items()})
        return ("C", o)

    template = build_template(out)
    return leaves, template


def _rebuild_out(template, tensors):
    kind = template[0]
    if kind == "T":
        return tensors[template[1]]
    if kind == "L":
        seq = [_rebuild_out(t, tensors) for t in template[2]]
        return tuple(seq) if template[1] == "tuple" else seq
    if kind == "D":
        return {k: _rebuild_out(v, tensors) for k, v in template[1].items()}
    return template[1]


class ModelMeta(LayerMeta):
    def __new__(mcs, name, bases, attrs):
        if "train_one_batch" in attrs:
            attrs["train_one_batch"] = ModelMeta.buffer_operation(
                attrs["train_one_batch"])
        return super().__new__(mcs, name, bases, attrs)

    @staticmethod
    def buffer_operation(func):
        """First call in graph mode builds + compiles the step; replays
        after (mirrors model.py:57-93)."""

        def wrapper(self, *args, **kwargs):
            if self._device is None:
                raise RuntimeError(
                    "call Model.compile([inputs], ...) before training — "
                    "params are shape-inferred from the compile inputs "
                    "(ref model.py:156)")
            if not (self.graph_mode and self.training):
                if getattr(self, "_health_monitor", None) is not None \
                        and self.training:
                    return self._eager_health_step(func, args, kwargs)
                return func(self, *args, **kwargs)
            if self._compiled_step is None:
                self._build_step(func, args, kwargs)
            return self._invoke_step(args)

        wrapper.__wrapped__ = func
        return wrapper


class Model(Layer, metaclass=ModelMeta):
    """Base user model: subclass, define `forward` and (optionally)
    `train_one_batch` (ref model.py:103)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.training = True
        self.graph_mode = True
        self.sequential = False
        self._optimizer = None
        self._device = None
        self._compiled_step = None
        self._step_execs = {}   # AOT executables per abstract signature
        self._eval_execs = {}
        self._step_stats = {"compile_s": 0.0, "steps": 0}
        self._health_monitor = None
        self._health_steps = 0

    # ---- configuration (ref model.py:185-243) ----------------------------
    def set_optimizer(self, opt):
        self._optimizer = opt

    def set_health_monitor(self, monitor):
        """Attach (or detach, with None) a health.HealthMonitor. The
        monitor's policy is STATIC in the compiled step (skip_step bakes
        an in-graph conditional commit into the executable), so any
        already-compiled step is dropped and rebuilt on the next call."""
        prev = self._health_monitor
        self._health_monitor = monitor
        self._compiled_step = None
        if monitor is not None:
            health.set_active_monitor(monitor)  # /healthz finds it here
        elif prev is not None and health.active_monitor() is prev:
            # detaching clears the process registration only when it is
            # ours — another model's live monitor keeps serving /healthz
            health.set_active_monitor(None)
        return monitor

    @property
    def optimizer(self):
        return self._optimizer

    def graph(self, mode=True, sequential=False):
        """Turn graph (jit) execution on/off after compile
        (ref model.py:224). `sequential=True` is the serial debug mode
        (jax.disable_jit), mirroring the reference's RunInSerial."""
        if mode == self.graph_mode and sequential == self.sequential:
            return  # idempotent: keep the compiled executables
        self.graph_mode = mode
        self.sequential = sequential
        if isinstance(self._compiled_step, dict):
            self._compiled_step = {}   # drop stale-flag executables
            self._step_execs = {}
            self._dispatch_cache = {}
        self._compiled_eval = None
        self._eval_execs = {}

    def compile(self, inputs, is_train=True, use_graph=False,
                sequential=False, pipeline_axis=None, n_micro=1,
                pipeline_schedule="gpipe", amp=None,
                eval_buckets="auto", health=None):
        """Dummy forward with concrete inputs to init all params
        (ref model.py:156-184).

        pipeline_axis/n_micro: mesh axis + microbatch count for pipeline
        execution; consumed by pipeline-capable models (e.g.
        models.transformer.PipelinedGPT) at param-init time.
        pipeline_schedule: "gpipe" (autodiff through the forward scan; all
        microbatch residuals live until backward) or "1f1b" (fused
        fwd+bwd interleave with in-schedule loss; in-flight activations
        bounded by ~2*stages, stage vjp rematerialized).

        amp: compute dtype for mixed-precision training ("bfloat16"):
        fp32 master weights with differentiable casts at matmul/conv
        boundaries; normalizations and losses stay fp32 (VERDICT r1 #14).

        eval_buckets: pad varying eval batch sizes to power-of-two buckets
        (O(log B) compiled variants instead of a retrace per size). Only
        valid when forward's outputs are all per-sample — a forward that
        reduces over the batch dim would average in the padding. Default
        "auto": the first eval call detects whether every output is
        per-sample (leading dim == batch) and enables bucketing for later
        batch sizes only if so; True forces it (loud error on
        non-per-sample outputs), False disables it."""
        assert len(inputs) > 0 and isinstance(inputs[0], Tensor)
        self._device = inputs[0].device
        self.graph_mode = use_graph
        self.sequential = sequential
        assert pipeline_schedule in ("gpipe", "1f1b"), pipeline_schedule
        self.pipeline_axis = pipeline_axis
        self.n_micro = n_micro
        self.pipeline_schedule = pipeline_schedule
        if amp in ("bf16", True):
            amp = "bfloat16"
        self.amp = amp
        self.eval_buckets = eval_buckets
        if health is not None:
            # a health.HealthMonitor instance; True means "default
            # monitor, warn policy", False detaches. Routed through
            # set_health_monitor so re-compiling an already-trained
            # model drops the stale executables (the policy is baked
            # into the compiled step).
            from . import health as _health
            if health is False:
                self.set_health_monitor(None)
            elif health is True:
                self.set_health_monitor(_health.HealthMonitor())
            elif isinstance(health, _health.HealthMonitor):
                self.set_health_monitor(health)
            else:
                raise TypeError(
                    f"health= expects a health.HealthMonitor, True, "
                    f"False, or None; got {type(health).__name__}")
        prev = autograd.training
        autograd.training = False  # init pass builds no tape
        try:
            self.forward(*inputs)
        finally:
            autograd.training = prev
        self.train(is_train)
        if self._optimizer is not None:
            self._optimizer.setup(self.get_params().values())

    def train(self, mode: bool = True):
        self.training = mode
        autograd.training = mode

    def eval(self):
        self.train(False)

    # ---- default hooks ---------------------------------------------------
    def forward(self, *args, **kwargs):
        raise NotImplementedError

    def train_one_batch(self, *args, **kwargs):
        raise NotImplementedError

    def __call__(self, *args, **kwargs):
        prev_cd = autograd.compute_dtype
        if getattr(self, "amp", None) is not None:
            autograd.compute_dtype = self.amp  # eager path; jitted steps
        try:                                   # set it at trace time too
            if self.training:
                return self.train_one_batch(*args, **kwargs)
            if self.graph_mode and self._device is not None and not kwargs \
                    and all(isinstance(a, Tensor) for a in args):
                # span -> the goodput `eval` bucket (a first-call AOT
                # build nests an introspect.build span, netted out)
                with observe.span("model.eval"):
                    return self._eval_step(args)
            return self.forward(*args, **kwargs)
        finally:
            autograd.compute_dtype = prev_cd

    # ---- the jitted step -------------------------------------------------
    def _build_step(self, func, example_args, kwargs):
        # span -> the goodput `compile` bucket (trace prep; the XLA
        # backend build itself lands under introspect.build)
        with observe.span("model.build"):
            self._build_step_impl(func, example_args, kwargs)

    def _build_step_impl(self, func, example_args, kwargs):
        from .opt import DistOpt  # local import to avoid cycle

        t0 = time.perf_counter()
        opt = self._optimizer
        if opt is not None:
            opt.setup(self.get_params().values())
        # memory-ledger birth-site hook: params (re-read per snapshot —
        # donation replaces the buffers every step) and the retained
        # step inputs the flight recorder would snapshot
        memory.track_model(self)
        # shard_map whenever a multi-device mesh is attached — the data
        # axis may be size 1 when the mesh is carved for tp/pp only
        dist = (isinstance(opt, DistOpt)
                and opt.communicator.mesh is not None
                and opt.communicator.mesh.size > 1)
        if dist:
            # Expert-parallel layers REQUIRE the gradient reduction to
            # cover their ep axis (tuple DistOpt axis): reducing over data
            # alone leaves each ep rank's replicated expert tables updated
            # from only its own slice grads — silent divergence, so refuse.
            mesh_axes = set(opt.communicator.mesh.shape.keys())
            red_axes = set(opt.axis if isinstance(opt.axis, tuple)
                           else (opt.axis,))
            stack = [self]
            while stack:
                lyr = stack.pop()
                stack.extend(getattr(lyr, "_layers", {}).values())
                ep = getattr(lyr, "ep_axis", None)
                if (ep is not None and hasattr(lyr, "num_experts")
                        and ep in mesh_axes and ep not in red_axes):
                    raise ValueError(
                        f"MoE layer routes experts over mesh axis '{ep}' "
                        f"but DistOpt reduces only over {sorted(red_axes)}"
                        f"; expert gradients would diverge across '{ep}'. "
                        f"Use DistOpt(axis={tuple(sorted(red_axes) + [ep])}"
                        f", mesh=mesh)")

        states = self.get_states()
        state_tensors = list(states.values())
        param_ids = {id(t) for t in self.get_params().values()}
        aux_idx = [i for i, t in enumerate(state_tensors)
                   if id(t) not in param_ids]
        dev = self._device
        monitor = self._health_monitor
        health_on = monitor is not None
        group_of = self._health_groups() if health_on else None
        # skip_step bakes an in-graph conditional commit into the step:
        # params/opt state select their PRE-step values when the agreed
        # nonfinite flag fires (donation is input->output aliasing, so
        # the old buffers are legal select operands)
        skip_in_graph = health_on and monitor.policy == "skip_step"

        tensor_pos = [i for i, a in enumerate(example_args)
                      if isinstance(a, Tensor)]
        static_args = {i: a for i, a in enumerate(example_args)
                       if not isinstance(a, Tensor)}
        self._tensor_pos = tensor_pos
        self._static_args = static_args
        # dispatch fast path: the per-step static-arg guard re-checks
        # values against these without rebuilding/comparing dicts
        self._n_call_args = len(example_args)
        self._static_items = tuple(sorted(static_args.items()))
        out_template_box = {}

        def make_step(tag):
            """Build + jit the step for one static step-tag. Tag 0 is the
            only tag for ordinary optimizers; DistOpt's partial-update
            strategy rotates tags so each compiled variant contains ONLY
            its parameter partition's collectives (true bandwidth rotation,
            unlike a runtime mask — resolves the opt.py partial NOTE)."""

            def step(state_arrs, opt_arrs, rng, input_arrs):
                if opt is not None:
                    opt._partial_static_idx = tag
                if dist:
                    # flattened rank (communicator handles tuple axes for
                    # multi-axis reductions like DP+EP)
                    dev.rng_state = jax.random.fold_in(
                        rng, opt.communicator.rank())
                else:
                    dev.rng_state = rng
                for t, a in zip(state_tensors, state_arrs):
                    t.data = a
                if opt is not None and opt_arrs:
                    opt.load_state_arrays(opt_arrs)
                call_args = []
                j = 0
                for i in range(len(example_args)):
                    if i in static_args:
                        call_args.append(static_args[i])
                    else:
                        call_args.append(Tensor(data=input_arrs[j],
                                                device=dev,
                                                requires_grad=False))
                        j += 1
                autograd.training = True
                prev_cd = autograd.compute_dtype
                autograd.compute_dtype = getattr(self, "amp", None)
                col = None
                if health_on:
                    col = health.StepStatsCollector(group_of)
                    health._set_collector(col)
                try:
                    out = func(self, *call_args, **kwargs)
                finally:
                    if health_on:
                        health._set_collector(None)
                    autograd.compute_dtype = prev_cd
                    if opt is not None:
                        # trace-time tag must not leak into later EAGER
                        # partial updates (they rotate via a host counter)
                        opt._partial_static_idx = None
                out_leaves, template = _flatten_out(out)
                out_template_box["t"] = template
                outs = [o.data for o in out_leaves]
                if dist:
                    # scalars (loss): average across shards; batched
                    # outputs: gather to global batch so callers see one
                    # coherent result
                    outs = [lax.pmean(o, opt.axis) if o.ndim == 0
                            else lax.all_gather(o, opt.axis, axis=0,
                                                tiled=True)
                            for o in outs]
                new_states = [t.data for t in state_tensors]
                if dist:
                    # non-param states (BN running stats) differ per shard:
                    # average them (syncBN-style) so the replicated
                    # out-spec holds
                    for i in aux_idx:
                        new_states[i] = lax.pmean(new_states[i], opt.axis)
                new_opt = opt.state_arrays() if opt is not None else []
                hstats = {}
                if health_on:
                    hstats = col.finalize(
                        comm=opt.communicator if dist else None)
                    if skip_in_graph:
                        # conditional commit: the whole update — params,
                        # aux states, opt slots, the step counter — rolls
                        # back atomically on every shard (the flag is the
                        # agreed cross-host verdict)
                        new_states = health.apply_skip(
                            hstats, state_arrs, new_states)
                        new_opt = health.apply_skip(
                            hstats, opt_arrs, new_opt)
                new_rng = jax.random.split(rng, 1)[0] if dist \
                    else dev.rng_state
                return new_states, new_opt, new_rng, outs, hstats

            if dist:
                from jax.sharding import PartitionSpec as P
                mesh = opt.communicator.mesh
                wrapped = jax.shard_map(
                    step, mesh=mesh,
                    in_specs=(state_in, opt_in, P(), P(opt.axis)),
                    out_specs=(state_in, opt_in, P(), P(), P()),
                    check_vma=False)
            else:
                wrapped = step
            if self.sequential:
                # RunGraph(sequential=true) parity (ref device.cc / SURVEY
                # §2.1): execute ops one-by-one eagerly for debugging —
                # op-level python breakpoints and immediate error locations
                # instead of one fused XLA program
                def serial(*a):
                    with jax.disable_jit():
                        return wrapped(*a)
                return serial
            return jax.jit(wrapped, donate_argnums=(0, 1))

        self._dist_shardings = None
        state_in = opt_in = None
        if dist:
            from jax.sharding import PartitionSpec as P, NamedSharding
            mesh = opt.communicator.mesh
            assert mesh is not None, \
                "DistOpt needs a mesh for multi-device training"

            def sanitize(spec):
                """Drop spec axes the mesh doesn't carry: a model built
                with tp_axis="tp" but trained on a {data, pp} mesh keeps
                those params REPLICATED (the layer forwards gate their
                collectives on axis_bound, so the math degrades to the
                serial path consistently)."""
                if spec is None:
                    return None
                axes = set(mesh.shape.keys())
                out = []
                for el in spec:
                    if el is None:
                        out.append(None)
                    elif isinstance(el, tuple):
                        kept = tuple(a for a in el if a in axes)
                        out.append(kept if kept else None)
                    else:
                        out.append(el if el in axes else None)
                if not any(e is not None for e in out):
                    return None
                return P(*out)

            # TP-sharded params (Tensor.spec set by tp_axis layers) enter
            # the shard_map partitioned; everything else is replicated. A
            # plain P() prefix is kept in the no-TP case so strategies with
            # dynamically growing optimizer state (sparse residuals) still
            # pytree-match.
            sanitized = [sanitize(getattr(t, "spec", None))
                         for t in state_tensors]
            state_specs = [s or P() for s in sanitized]
            has_tp = any(s is not None for s in sanitized)
            if has_tp:
                state_in = state_specs
                opt_in = [sanitize(s) or P() for s in opt.state_specs()]
                self._dist_shardings = (
                    NamedSharding(mesh, P()),
                    NamedSharding(mesh, P(opt.axis)),
                    [NamedSharding(mesh, s) for s in state_specs],
                    [NamedSharding(mesh, s) for s in opt_in],
                )
            else:
                state_in = opt_in = P()
                self._dist_shardings = (NamedSharding(mesh, P()),
                                        NamedSharding(mesh, P(opt.axis)),
                                        None, None)
        self._state_tensors = state_tensors
        self._out_template_box = out_template_box
        self._step_builder = make_step
        self._compiled_step = {}   # step-tag -> jitted executable
        self._step_execs = {}      # (tag, abstract sig) -> AOT executable
        self._step_sigs = set()    # (tag, input shapes) variants seen
        # (tag, abstract sig) -> [step_fn, flops, sig, recorded]:
        # everything the cached dispatch needs, resolved once per variant
        # so the hot path does O(#inputs) work (the key) instead of
        # rebuilding signatures/cache lookups every step
        self._dispatch_cache = {}
        self._step_stats["compile_s"] = time.perf_counter() - t0
        observe.record_step_build(self._step_stats["compile_s"])

    def _static_mismatch(self, args):
        """Rebuild the full dict comparison only to phrase the error —
        the per-step guard already proved a mismatch (or a change in
        which positions carry Tensors)."""
        cur_static = {i: a for i, a in enumerate(args)
                      if not isinstance(a, Tensor)}
        raise ValueError(
            f"graph mode compiled with static args {self._static_args}, "
            f"got {cur_static}; non-Tensor arguments cannot change "
            "between calls (recompile by resetting the model, or run "
            "with use_graph=False)")

    def _invoke_step(self, args):
        opt = self._optimizer
        dev = self._device
        # non-Tensor args (dist_option, spars, ...) are baked into the
        # compiled step at trace time; changing them later must not be
        # silently ignored. Positions were fixed at build time, so the
        # hot path re-checks values in place instead of building and
        # comparing a fresh dict every step.
        if len(args) != self._n_call_args:
            self._static_mismatch(args)
        for i, v in self._static_items:
            a = args[i]
            if isinstance(a, Tensor) or a != v:
                self._static_mismatch(args)
        for i in self._tensor_pos:
            if not isinstance(args[i], Tensor):
                self._static_mismatch(args)
        state_arrs = [t.data for t in self._state_tensors]
        opt_arrs = opt.state_arrays() if opt is not None else []
        input_arrs = [args[i].data for i in self._tensor_pos]
        self._last_input_arrs = input_arrs
        rng = dev.rng_state
        if self._dist_shardings is not None:
            # replicate (or TP-shard) states over the mesh, shard the batch
            # on the data axis (a no-op after step 1: outputs already carry
            # these shardings, so only fresh host batches actually move)
            rep, shard, state_sh, opt_sh = self._dist_shardings

            def put(a, sh):
                if getattr(a, "sharding", None) == sh:
                    return a
                if isinstance(a, jax.Array) and not a.is_fully_addressable:
                    # already a global array (a previous step's output);
                    # re-putting is impossible and unnecessary
                    return a
                if jax.process_count() > 1:
                    # multi-host: device_put cannot scatter across hosts.
                    # Every process holds the FULL host value (params init
                    # from a shared seed, batches fed as global arrays), so
                    # each builds its addressable shards by indexing into
                    # it — correct for replicated AND partitioned specs.
                    if jnp.issubdtype(getattr(a, "dtype", None),
                                      jax.dtypes.prng_key):
                        # typed keys can't pass np.asarray; ship the raw
                        # key data (rng shardings are replicated, so the
                        # spec is rank-agnostic)
                        kd = np.asarray(jax.random.key_data(a))
                        g = jax.make_array_from_callback(
                            kd.shape, sh, lambda idx: kd[idx])
                        return jax.random.wrap_key_data(g)
                    host = np.asarray(a)
                    return jax.make_array_from_callback(
                        host.shape, sh, lambda idx: host[idx])
                return jax.device_put(a, sh)

            if state_sh is None:
                state_arrs = [put(a, rep) for a in state_arrs]
                opt_arrs = [put(a, rep) for a in opt_arrs]
            else:
                state_arrs = [put(a, s)
                              for a, s in zip(state_arrs, state_sh)]
                opt_arrs = [put(a, s)
                            for a, s in zip(opt_arrs, opt_sh)]
            rng = put(rng, rep)
            input_arrs = [put(a, shard) for a in input_arrs]
        tag = opt.step_tag() if opt is not None else 0
        fn = self._compiled_step.get(tag)
        if fn is None:
            fn = self._compiled_step[tag] = self._step_builder(tag)
        obs = observe.is_enabled()
        bs = None
        if input_arrs and getattr(input_arrs[0], "ndim", 0):
            bs = input_arrs[0].shape[0]
        step_fn = fn
        exec_key = None
        variant = None
        cold_jit = False  # this dispatch pays a fresh jit trace+compile
        if not self.sequential:
            # dispatch fast path: one O(#inputs) key resolves everything
            # a repeat step needs — the AOT executable (or jit fallback),
            # its harvested flops, and the already-recorded observe
            # signature — so the cached path rebuilds no signatures and
            # touches no introspection. len(opt_arrs) is in the key
            # because the sparse strategies GROW their optimizer state
            # (new residual slots) between steps.
            exec_key = (tag,
                        tuple((tuple(a.shape), str(a.dtype))
                              for a in input_arrs),
                        len(opt_arrs))
            variant = self._dispatch_cache.get(exec_key)
            if variant is None:
                variant, cold_jit = self._dispatch_slow_path(
                    exec_key, tag, fn, state_arrs, opt_arrs, rng,
                    input_arrs, bs)
            step_fn = variant[0]
            # the MFU gauge must use the DISPATCHED variant's flops, not
            # the most recently built one (a partial-batch build would
            # otherwise skew later full-batch readings); 0 for a
            # negative-cached variant disables the gauge instead
            introspect.note_step_flops(variant[1])
        else:
            introspect.note_step_flops(0)  # sequential: no AOT variant
        if obs:
            # (tag, input-shape) signature: jit retraces exactly when it
            # changes, so first-seen == a compile (first ever) or a
            # recompile (new batch-size class / step tag). A variant
            # records at most once (its flag), so the cached path skips
            # the signature rebuild + set lookup entirely.
            if variant is not None:
                if not variant[3]:
                    variant[3] = True
                    self._record_step_sig(variant[2], bs,
                                          state_arrs, opt_arrs)
            else:  # sequential debug path: no variant cache
                sig = (tag,
                       tuple(getattr(a, "shape", ()) for a in input_arrs))
                self._record_step_sig(sig, bs, state_arrs, opt_arrs)
            t_obs = time.perf_counter()
        profiling = (dev.verbosity > 0 and
                     self._step_stats["steps"] >= dev.skip_iteration)
        if profiling:
            if dev.cost_analysis is None and dev.verbosity >= 2:
                dev.cost_analysis = self.step_cost_analysis() \
                    if self._step_stats["steps"] > 0 else {}
            t0 = time.perf_counter()
        # span -> the goodput `step` bucket (held pending until the
        # health verdict below, so a discarded update reclassifies to
        # `health_skip`); covers dispatch and, when profiling, the fence.
        # The watchdog guard arms the `step` deadline over the same
        # region (nested no-op when a TrainController's outer guard is
        # already armed); a cold jit fallback's build span taints the
        # entry, so first-compile time neither breaches nor calibrates
        # tag attr: the regress detector baselines each optimizer-tag
        # variant separately (different tags dispatch different
        # executables with different per-step costs)
        with watchdog.guard("step"), observe.span("model.step", tag=tag):
            try:
                if cold_jit:
                    # nested mapped span: the fresh trace+compile nets
                    # out of `step` and lands in the `compile` bucket
                    with observe.span("model.jit_fallback"):
                        new_states, new_opt, new_rng, outs, hstats = \
                            step_fn(state_arrs, opt_arrs, rng, input_arrs)
                else:
                    new_states, new_opt, new_rng, outs, hstats = step_fn(
                        state_arrs, opt_arrs, rng, input_arrs)
            except Exception as step_exc:
                if memory.is_resource_exhausted(step_exc):
                    # the device allocator ran out: re-dispatching via
                    # the jit fallback would just OOM again — dump the
                    # forensics bundle (timeline, region breakdown,
                    # top-K arrays, executable manifest) and re-raise
                    memory.handle_oom(step_exc, key="step")
                    raise
                if step_fn is fn:
                    raise
                # the AOT executable rejected the call (e.g. an optimizer
                # slot changed shape in place, invisible to exec_key):
                # negative-cache the signature so jit owns it from now on —
                # correctness over telemetry, and no rebuild-per-step churn
                self._step_execs[exec_key] = None
                if variant is not None:
                    variant[0] = fn     # later fast-path hits go straight
                    variant[1] = 0.0    # to jit, with the MFU gauge off
                introspect.note_step_flops(0)  # this step: jit-dispatched
                with observe.span("model.jit_fallback"):
                    new_states, new_opt, new_rng, outs, hstats = fn(
                        state_arrs, opt_arrs, rng, input_arrs)
            if profiling:
                jax.block_until_ready(new_states)
                fenced = time.perf_counter() - t0
                dev.step_times.append(fenced)
                observe.record_step_fenced(fenced)
            if self._health_monitor is not None and hstats:
                # fetch the stats INSIDE the span: on an async backend
                # this is the step's sync point, so the span records the
                # device step's real wall time (not just dispatch) —
                # without a monitor or profiling, only dispatch time is
                # attributable and the remainder lands in `other`
                hstats = jax.device_get(hstats)
        for t, a in zip(self._state_tensors, new_states):
            t.data = a
        if opt is not None and new_opt:
            opt.load_state_arrays(new_opt)
        if self._dist_shardings is not None and (
                not isinstance(new_rng, jax.Array)
                or new_rng.is_fully_addressable):
            # un-replicate the key so later eager/single-device work (fresh
            # param init, eval) doesn't inherit a mesh sharding. (On a
            # multi-host mesh the key is not addressable here; it stays
            # global and step feeds consume it in place.)
            new_rng = jax.device_put(new_rng, dev.jax_device)
        dev.rng_state = new_rng
        self._step_stats["steps"] += 1
        if obs:
            observe.record_step(time.perf_counter() - t_obs,
                                batch=bs, tag=tag, device=dev)
        if self._health_monitor is not None:
            # stats were fetched (and the step thereby fenced) inside
            # the model.step span above; this feed is host-side only
            action = self._health_feed(hstats, self._last_input_arrs,
                                       in_graph_skip=True, fetched=True)
            if action == "skip":
                # the update was discarded in-graph: this step's wall
                # time produced nothing — move it out of `step`
                goodput.mark_step_skipped()
        tensors = [Tensor(data=a, device=dev, requires_grad=False)
                   for a in outs]
        return _rebuild_out(self._out_template_box["t"], tensors)

    def _dispatch_slow_path(self, exec_key, tag, fn, state_arrs, opt_arrs,
                            rng, input_arrs, bs):
        """First dispatch of a (tag, abstract-signature) variant: the
        explicit trace -> lower -> compile staging happens here ONLY, so
        compile-phase timing, cost/memory harvesting and recompile blame
        all land at build/retrace time; the resolved executable (the
        same bytes jit would have cached), its flops, and the observe
        signature are cached in a slim per-variant record for every
        later step. Returns (variant_record, cold_jit)."""
        entry = self._step_execs.get(exec_key, _AOT_MISS)
        cold_jit = False
        if entry is _AOT_MISS:
            asig = introspect.signature(
                (state_arrs, opt_arrs, rng, input_arrs),
                names=("state", "opt", "rng", "arg"), tag=tag,
                static=repr(sorted(
                    (i, repr(v))
                    for i, v in self._static_args.items())),
                donated=(0, 1), batch_hint=bs)
            aot, rec = introspect.build_compiled(
                fn, (state_arrs, opt_arrs, rng, input_arrs),
                "step", asig, device=self._device)
            # a failed build negative-caches as None so the cached path
            # never re-pays a staging attempt per step
            entry = self._step_execs[exec_key] = None if aot is None \
                else (aot, float((rec or {}).get("cost", {})
                                 .get("flops", 0) or 0))
            # staging just failed: the jit dispatch below compiles
            # cold — goodput must book that as compile, not step
            cold_jit = aot is None
            if entry is not None and "t" not in self._out_template_box:
                # warm-store hit: the executable came back deserialized,
                # so the original step fn was never traced and the
                # out-template side channel is empty. One abstract trace
                # (no lower/compile) recovers it; snapshot + restore the
                # state the trace mutates (lower_step's contract) so no
                # tracer escapes into eager work.
                dev = self._device
                opt_obj = self._optimizer
                snap_state = [t.data for t in self._state_tensors]
                snap_opt = list(opt_obj.state_arrays()) \
                    if opt_obj is not None else []
                snap_rng = dev.rng_state
                snap_training = autograd.training
                try:
                    jax.eval_shape(fn, state_arrs, opt_arrs, rng,
                                   input_arrs)
                except Exception:
                    # template unrecoverable: drop the warm variant and
                    # let plain jit own the signature — its first
                    # dispatch traces the fn and fills the box
                    entry = self._step_execs[exec_key] = None
                    cold_jit = True
                finally:
                    autograd.training = snap_training
                    dev.rng_state = snap_rng
                    for t, a in zip(self._state_tensors, snap_state):
                        t.data = a
                    if opt_obj is not None and snap_opt:
                        opt_obj.load_state_arrays(snap_opt)
        if entry is not None:
            step_fn, flops = entry
        else:
            step_fn, flops = fn, 0.0  # negative-cached: plain jit owns it
        sig = (tag, tuple(getattr(a, "shape", ()) for a in input_arrs))
        variant = self._dispatch_cache[exec_key] = \
            [step_fn, flops, sig, False]
        return variant, cold_jit

    def _record_step_sig(self, sig, bs, state_arrs, opt_arrs):
        """First sighting of a (tag, input-shape) signature == a jit
        trace: record the compile (or recompile, when other signatures
        exist) with the donated-buffer bytes. Shared by the variant
        fast path and the sequential debug path."""
        if sig in self._step_sigs:
            return
        observe.record_compile(
            bs, recompile=bool(self._step_sigs),
            donated_bytes=sum(
                int(getattr(a, "nbytes", 0))
                for a in (*state_arrs, *opt_arrs)))
        self._step_sigs.add(sig)

    # ---- training health (singa_tpu.health) ------------------------------
    def _health_groups(self):
        """{id(param): layer group} — the first path component of the
        param's get_params() name ("l1.W" -> "l1"), the granularity the
        per-group norm/ratio stats aggregate at."""
        return {id(t): name.split(self.sep, 1)[0]
                for name, t in self.get_params().items()}

    def _health_feed(self, hstats, input_arrs, in_graph_skip,
                     fetched=False):
        mon = self._health_monitor
        self._health_steps += 1
        # _invoke_step fetches the stats inside the model.step span (the
        # fetch IS the step fence); don't traverse the tree a second time
        host = hstats if fetched else (
            jax.device_get(hstats) if hstats else {})
        host = host or {}
        provider = None
        if input_arrs is not None and mon.snapshot_batch:
            provider = lambda: [np.asarray(jax.device_get(a))  # noqa: E731
                                for a in input_arrs]
        return mon.on_step(host, step=self._health_steps,
                           batch_provider=provider,
                           amp=getattr(self, "amp", None) is not None,
                           in_graph_skip=in_graph_skip)

    def _eager_health_step(self, func, args, kwargs):
        """Eager-mode health: the same collector, finalized eagerly.
        skip_step's rollback is part of the compiled step, so eager
        anomalies get warn/halt semantics only (in_graph_skip=False).
        Single-process scope: finalize runs with no communicator —
        eager mode cannot execute mesh collectives anyway (psum outside
        a shard_mapped step has no bound axis), so eager + DistOpt at
        world_size > 1 is out of scope here as it is for training."""
        col = health.StepStatsCollector(self._health_groups())
        health._set_collector(col)
        try:
            out = func(self, *args, **kwargs)
        finally:
            health._set_collector(None)
        self._health_feed(col.finalize(),
                          [a.data for a in args if isinstance(a, Tensor)],
                          in_graph_skip=False)
        return out

    # ---- minimal training loop -------------------------------------------
    def fit(self, data, epochs=1, verbose=0, prefetch_to_device=0):
        """Host-side training loop over `data`, an iterable of per-batch
        argument tuples for `train_one_batch` (re-iterated each epoch, so
        pass a list/dataset, not a one-shot generator). Returns the list
        of per-epoch mean losses (by convention the second element of the
        step's return, or the whole return when it is a single Tensor).

        prefetch_to_device=N wraps each epoch's iterator in an
        overlap.DevicePrefetcher: a background thread moves up to N
        batches to the device (with the model's input sharding) ahead of
        consumption, so host batch assembly and host->device transfer
        overlap the previous step's execution instead of serializing
        into the goodput `data_wait` bucket. The prefetcher is closed on
        every exit path — normal end of epoch, an early break, or a
        HealthError raised out of the loop.

        This is where the health layer meets the loop: every step feeds
        the attached HealthMonitor (skip_step discards bad updates
        in-graph without breaking the loop; halt raises HealthError out
        of fit with the flight-recorder bundle already on disk AND the
        epoch's partial progress attached as `HealthError.partial` —
        {"epoch", "steps_completed", "losses", "last_loss"} — so a
        supervising controller can log/checkpoint what the epoch did
        achieve instead of losing it with the raise)."""
        history = []
        _end = object()
        for epoch in range(epochs):
            losses = []
            with observe.span("model.fit_epoch", epoch=epoch):
                it = iter(data)
                prefetcher = None
                if prefetch_to_device:
                    from . import overlap
                    prefetcher = overlap.DevicePrefetcher(
                        it, model=self, size=int(prefetch_to_device))
                    it = prefetcher
                try:
                    while True:
                        # fetch wait measured per batch: the host-side
                        # pipeline stall signal (goodput `data_wait`; an
                        # iterator's own data.wait span nests, nets
                        # out). The watchdog arms the `data_wait`
                        # deadline over the same wait; `data.next` is
                        # its deterministic FaultPlan hook.
                        with observe.span("data.wait"), \
                                watchdog.guard("data_wait"):
                            from . import resilience
                            resilience.fault_point("data.next")
                            batch = next(it, _end)
                        if batch is _end:
                            break
                        if not isinstance(batch, (tuple, list)):
                            batch = (batch,)
                        out = self(*batch)
                        loss = out[1] if isinstance(out, (tuple, list)) \
                            and len(out) > 1 else out
                        if isinstance(loss, Tensor):
                            # keep the device scalar; fetch once per
                            # epoch so the loop stays async-dispatched
                            losses.append(loss.data)
                except health.HealthError as e:
                    # a mid-epoch halt must not discard the epoch's loss
                    # history: surface the partial progress on the error
                    # (one transfer, same as the happy path below)
                    vals = [float(np.asarray(a))
                            for a in jax.device_get(losses)]
                    e.partial = {
                        "epoch": epoch,
                        "steps_completed": len(vals),
                        "losses": vals,
                        "last_loss": vals[-1] if vals else None,
                    }
                    raise
                finally:
                    if prefetcher is not None:
                        prefetcher.close()
            if not losses:
                raise ValueError(
                    f"fit epoch {epoch} saw no batches - `data` must be "
                    "re-iterable across epochs (a list, not a generator)")
            # ONE transfer for the whole epoch (was one device_get per
            # element — a host<->device round-trip per step)
            vals = [float(np.asarray(a)) for a in jax.device_get(losses)]
            mean = sum(vals) / len(vals)
            history.append(mean)
            if verbose:
                print(f"epoch {epoch}: loss {mean:.6f} "
                      f"({len(vals)} steps)")
        return history

    def lower_step(self, tag=0):
        """Re-lower a compiled step variant for inspection (HLO text, cost
        analysis). Lowering re-traces the step, which assigns tracers into
        dev.rng_state and the state Tensors as a side effect — snapshot and
        restore them so no tracer escapes into later eager work."""
        if not self._compiled_step or \
                getattr(self, "_last_input_arrs", None) is None:
            return None
        fn = self._compiled_step.get(tag)
        if fn is None:
            return None
        opt = self._optimizer
        dev = self._device
        snap_state = [t.data for t in self._state_tensors]
        snap_opt = list(opt.state_arrays()) if opt is not None else []
        snap_rng = dev.rng_state
        state_arrs, opt_arrs, rng = snap_state, snap_opt, snap_rng
        if self._dist_shardings is not None:
            rep, _, state_sh, opt_sh = self._dist_shardings
            state_arrs = [jax.device_put(a, s) for a, s in
                          zip(state_arrs, state_sh)] if state_sh else \
                [jax.device_put(a, rep) for a in state_arrs]
            opt_arrs = [jax.device_put(a, s) for a, s in
                        zip(opt_arrs, opt_sh)] if opt_sh else \
                [jax.device_put(a, rep) for a in opt_arrs]
            rng = jax.device_put(rng, rep)
        snap_training = autograd.training
        try:
            return fn.lower(state_arrs, opt_arrs, rng,
                            self._last_input_arrs)
        finally:
            # restore the PRE-replication snapshots: leaving mesh-committed
            # arrays in globally shared state would poison later
            # single-device work
            autograd.training = snap_training
            dev.rng_state = snap_rng
            for t, a in zip(self._state_tensors, snap_state):
                t.data = a
            if opt is not None and snap_opt:
                opt.load_state_arrays(snap_opt)

    def step_cost_analysis(self):
        """XLA cost analysis of the compiled training step (flops, bytes
        accessed, ...) — the TPU analog of the reference's per-node
        profiling tables (scheduler.cc:240-295). Requires at least one
        graph-mode train call. Returns {} if unavailable."""
        try:
            lowered = self.lower_step()
            if lowered is None:
                return {}
            ca = lowered.compile().cost_analysis()
            return ca[0] if isinstance(ca, list) else (ca or {})
        except Exception:
            return {}

    # ---- jitted inference (graph mode for eval; the reference replays its
    # buffered graph for eval too, model.py:94-100) ------------------------
    def _eval_invoke(self, concrete, arrs, nb=None):
        """Eval forward through the AOT-staged executable cache: one
        executable per abstract input signature, built via
        introspect.build_compiled (compile-phase timing + recompile
        blame; `nb` is the PRE-padding batch so a bucket crossing blames
        the true sizes). Falls back to the plain jit call when staging
        or dispatch fails."""
        key = tuple((tuple(a.shape), str(a.dtype)) for a in arrs)
        aot = self._eval_execs.get(key, _AOT_MISS)
        if aot is _AOT_MISS:
            asig = introspect.signature(
                (concrete, arrs), names=("state", "arg"), batch_hint=nb)
            aot, _rec = introspect.build_compiled(
                self._compiled_eval, (concrete, arrs), "eval", asig)
            if aot is not None and \
                    not hasattr(self, "_eval_template"):
                # warm-store hit: efwd was never traced, so the eval
                # out-template side channel is empty — one abstract
                # trace recovers it (same contract as the step path;
                # efwd's only other side effects are the trace counter
                # and state-tensor assignments restored below)
                snap_state = [t.data for t in self._eval_tensors]
                try:
                    jax.eval_shape(self._compiled_eval, concrete, arrs)
                except Exception:
                    aot = None  # jit owns it: first dispatch traces
                finally:
                    for t, a in zip(self._eval_tensors, snap_state):
                        t.data = a
            # None negative-caches a failed build: jit owns this shape
            self._eval_execs[key] = aot
            if aot is None:
                # fresh staging failure: the jit call compiles cold —
                # goodput books it as compile, not eval
                with observe.span("model.jit_fallback"):
                    return self._compiled_eval(concrete, arrs)
        if aot is None:
            return self._compiled_eval(concrete, arrs)
        try:
            return aot(concrete, arrs)
        except Exception:
            self._eval_execs[key] = None
            with observe.span("model.jit_fallback"):
                return self._compiled_eval(concrete, arrs)

    def _eval_step(self, args):
        if getattr(self, "_compiled_eval", None) is None:
            states = self.get_states()
            eval_tensors = list(states.values())

            def efwd(state_arrs, input_arrs):
                # host-side trace counter: jit re-runs this body only on a
                # retrace, so tests can assert bucketing avoids retraces
                self._eval_trace_count = \
                    getattr(self, "_eval_trace_count", 0) + 1
                for t, a in zip(eval_tensors, state_arrs):
                    t.data = a
                prev = autograd.training
                prev_cd = autograd.compute_dtype
                autograd.training = False
                autograd.compute_dtype = getattr(self, "amp", None)
                try:
                    out = self.forward(*[Tensor(data=a, device=self._device,
                                                requires_grad=False)
                                         for a in input_arrs])
                finally:
                    autograd.training = prev
                    autograd.compute_dtype = prev_cd
                leaves, template = _flatten_out(out)
                self._eval_template = template
                return [o.data for o in leaves]

            self._eval_tensors = eval_tensors
            self._compiled_eval = jax.jit(efwd)
            self._eval_execs = {}
        concrete = [t.data for t in self._eval_tensors]
        # batch-shape bucketing: pad the batch dim up to the next power of
        # two so varying eval sizes (e.g. the last partial batch) reuse
        # O(log B) compiled variants instead of retracing per size. Only
        # sound when every output is per-sample (leading dim == batch); a
        # forward that reduces over the batch would see the zero padding —
        # so the default "auto" mode probes the first (unbucketed) call's
        # output shapes and enables bucketing only when they are all
        # per-sample; compile(eval_buckets=True) forces it.
        arrs = [a.data for a in args]
        nb = arrs[0].shape[0] if arrs and arrs[0].ndim > 0 else None
        mode = getattr(self, "eval_buckets", "auto")
        enabled = (mode is True or
                   (mode == "auto"
                    and getattr(self, "_eval_per_sample", None) is True))
        bucket = None
        if enabled and nb is not None \
                and nb > 0 and all(
                a.ndim > 0 and a.shape[0] == nb for a in arrs):
            bucket = 1
            while bucket < nb:
                bucket *= 2
            if bucket != nb:
                arrs = [jnp.concatenate(
                    [a, jnp.zeros((bucket - nb,) + a.shape[1:], a.dtype)])
                    for a in arrs]
            else:
                bucket = None
        try:
            if self.sequential:
                # serial debug mode applies to inference too (RunInSerial)
                with jax.disable_jit():
                    outs = self._compiled_eval(concrete, arrs)
            else:
                outs = self._eval_invoke(concrete, arrs, nb)
        finally:
            # tracing assigns tracers into the state Tensors; put the real
            # arrays back so later eager/train calls see concrete buffers
            for t, a in zip(self._eval_tensors, concrete):
                t.data = a
        if bucket is not None:
            # the eval_buckets contract is "every output is per-sample";
            # enforce it loudly (ValueError, not assert: -O must not turn
            # this back into silent truncation of a fixed-size output that
            # merely matches the bucket)
            for o in outs:
                if o.ndim == 0 or o.shape[0] != bucket:
                    raise ValueError(
                        f"eval_buckets requires per-sample outputs; "
                        f"got shape {o.shape} with batch bucket {bucket} "
                        f"(compile with eval_buckets=False to retrace "
                        f"per shape instead)")
            outs = [o[:nb] for o in outs]
        elif mode == "auto" and nb is not None and \
                getattr(self, "_eval_per_sample", None) is not False and \
                nb not in getattr(self, "_eval_probed_nbs", ()):
            # auto-detect on unbucketed calls. Shape alone is not proof —
            # a batch-coupled output (softmax over axis 0) is batch-shaped
            # too — so PROBE semantics: re-run on the first half of the
            # batch and require out(x[:h]) == out(x)[:h]. The probe
            # re-runs once per NEW batch-size class (a coupling that was
            # numerically invisible at one size may not be at another),
            # and a failed re-probe permanently disables bucketing rather
            # than silently zero-padding a coupled model.
            shaped = all(o.ndim > 0 and o.shape[0] == nb for o in outs)
            ok = False
            if shaped and nb > 1:
                h = nb // 2
                try:
                    houts = self._eval_invoke(
                        concrete, [a[:h] for a in arrs], h)
                    ok = all(
                        np.allclose(np.asarray(jax.device_get(ho)),
                                    np.asarray(jax.device_get(o))[:h],
                                    rtol=1e-5, atol=1e-6)
                        for ho, o in zip(houts, outs))
                except Exception:
                    ok = False
                finally:
                    for t, a in zip(self._eval_tensors, concrete):
                        t.data = a
            if not hasattr(self, "_eval_probed_nbs"):
                self._eval_probed_nbs = set()
            self._eval_probed_nbs.add(nb)
            self._eval_per_sample = shaped and ok
        tensors = [Tensor(data=a, device=self._device, requires_grad=False)
                   for a in outs]
        return _rebuild_out(self._eval_template, tensors)

    # ---- checkpointing (ref model.py:244-354) ----------------------------
    def save_states(self, fpath: str, aux_states: dict | None = None):
        """zip(tensor_dict.npz + states_attr.json), same layout as the
        reference so checkpoints are inspectable with stdlib tools."""
        states = {k: t.numpy() for k, t in self.get_states().items()}
        if aux_states:
            for k, v in aux_states.items():
                states[f"aux.{k}"] = np.asarray(
                    v.numpy() if isinstance(v, Tensor) else v)
        attrs = {k: {"shape": list(v.shape), "dtype": str(v.dtype)}
                 for k, v in states.items()}
        # span -> the goodput `checkpoint` bucket, same as the orbax path
        with observe.span("checkpoint.save"):
            npz_buf = io.BytesIO()
            np.savez(npz_buf, **states)
            with zipfile.ZipFile(fpath, "w") as zf:
                zf.writestr("tensor_dict.npz", npz_buf.getvalue())
                zf.writestr("states_attr.json", json.dumps(attrs))
        observe.record_checkpoint_bytes(
            sum(int(v.nbytes) for v in states.values()))

    # ---- full training checkpoints (orbax) -------------------------------
    # save_states/load_states keep the reference's zip(npz+json) layout
    # for MODEL states; these save the full TRAINING state — params,
    # layer states, optimizer state, the device RNG — through orbax,
    # which writes sharded jax.Arrays per-shard (no host gather): the
    # pod-scale checkpoint path the zip format cannot be.
    def save_checkpoint(self, ckpt_dir: str, step: int = 0,
                        overwrite: bool = False, async_save: bool = True):
        """Write a resumable training checkpoint under `ckpt_dir/step_N`.
        Captures model states, optimizer state (slot buffers + step
        counter) and the device PRNG stream, so training resumed from it
        is bit-identical to uninterrupted training (tests/test_model.py::
        test_checkpoint_resume_equivalence). An existing COMPLETE step_N
        directory (one carrying a `step_N.manifest.json` sibling, the
        resilience layer's durability marker) raises unless
        `overwrite=True`; an existing step_N WITHOUT a manifest —
        usually an interrupted, half-written save — is reclaimed by
        default: renamed aside as `step_N.reclaimed` (data preserved,
        since a plain-API save never writes a manifest and may be a
        complete checkpoint) so a restarted job never wedges on its
        predecessor's debris.

        async_save=True (the default) routes the write through orbax's
        AsyncCheckpointer when this orbax has one: the call returns once
        the device->host snapshot is taken and the serialize/write
        overlaps training. The bytes are durable only after
        `singa_tpu.overlap.wait_for_checkpoints()` — auto-invoked by the
        next save, by `load_checkpoint`, and at interpreter exit — which
        also re-raises any deferred write failure. Pass async_save=False
        (or run on an old orbax) for the blocking write."""
        import jax
        import orbax.checkpoint as ocp
        from . import overlap
        from .device import get_default_device
        # barrier on the previous async save: at most one write is in
        # flight, and its deferred error surfaces HERE, not never
        overlap.wait_for_checkpoints()
        dev = self._device or get_default_device()
        rng = dev.rng_state
        if jnp.issubdtype(getattr(rng, "dtype", None), jax.dtypes.prng_key):
            rng = jax.random.key_data(rng)
        # RAW arrays throughout (no np.asarray): optimizer slots of
        # sharded params are themselves sharded jax.Arrays and orbax
        # writes them per-shard — a host gather here would defeat the
        # point (and fail outright on non-addressable multi-host arrays)
        opt_tree = {}
        res_tree = {}
        if self._optimizer is not None:
            opt_tree = {f"s{i}": a for i, a in
                        enumerate(self._optimizer.state_arrays())}
            # sparse error-feedback residuals are per-DEVICE state under a
            # replicated spec: save every device's buffer, not device 0's
            get_stacks = getattr(self._optimizer,
                                 "residual_device_stacks", None)
            if get_stacks is not None:
                res_tree = {f"r{i}": v for i, v in get_stacks().items()}
        tree = {
            "model": {k: t.data for k, t in self.get_states().items()},
            "opt": opt_tree,
            "res": res_tree,
            "rng": rng,
        }
        path = os.path.join(os.path.abspath(ckpt_dir), f"step_{step}")
        if os.path.isdir(path):
            from . import resilience
            if not overwrite \
                    and not resilience.is_complete_checkpoint(path):
                # no manifest == not PROVEN complete: usually the
                # controller's crashed-writer debris, but possibly a
                # fine checkpoint written by this plain API (which
                # never writes manifests). Vacate the step_N name by
                # setting the old dir ASIDE (any manifest file rides
                # along) instead of destroying it — a restarted job
                # never wedges on its predecessor's leftovers, and
                # nothing durable is ever silently lost.
                resilience.set_aside_checkpoint(path, ".reclaimed")
            elif overwrite:
                # a stale manifest must not mark the in-flight rewrite
                # as complete (discovery keys on manifest presence)
                try:
                    os.remove(resilience.manifest_path(path))
                except OSError:
                    pass
        nbytes = sum(int(getattr(a, "nbytes", 0) or 0)
                     for a in jax.tree_util.tree_leaves(tree))
        if async_save and overlap.start_async_save(path, tree,
                                                   force=overwrite):
            # blocking portion only (the snapshot) was spanned inside
            # start_async_save; the background write is the overlap
            observe.record_checkpoint_bytes(nbytes)
            return path
        ck = ocp.StandardCheckpointer()
        # span -> the goodput `checkpoint` bucket; the watchdog arms
        # the ckpt_save deadline over the blocking write
        with observe.span("checkpoint.save"), \
                watchdog.guard("ckpt_save"):
            ck.save(path, tree, force=overwrite)
            ck.wait_until_finished()
        # this blocking write is durable here: it supersedes any
        # recorded async-write failure for the same path
        overlap.clear_write_failed(path)
        observe.record_checkpoint_bytes(nbytes)
        return path

    def _restore_template(self, path):
        """Abstract restore targets carrying THIS process's current
        shardings, so orbax reads only the shards each host addresses —
        the multi-host restore path (every process calls load_checkpoint
        with the same path; arrays come back sharded exactly as the live
        training state is). Leaves whose live counterpart does not exist
        yet (sparse residual stacks, the rng key-data) fall back to the
        checkpoint's own metadata with a replicated sharding."""
        import jax
        import orbax.checkpoint as ocp

        def sds(a):
            return jax.ShapeDtypeStruct(a.shape, a.dtype,
                                        sharding=a.sharding)

        mesh = None
        if self._optimizer is not None:
            mesh = getattr(
                getattr(self._optimizer, "communicator", None),
                "mesh", None)

        def meta_leaf(m):
            # replicated target: correct on one host, and on a pod every
            # host holds the full (small) array
            if mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec
                return jax.ShapeDtypeStruct(
                    tuple(m.shape), np.dtype(m.dtype),
                    sharding=NamedSharding(mesh, PartitionSpec()))
            return jax.ShapeDtypeStruct(tuple(m.shape), np.dtype(m.dtype))

        meta = ocp.StandardCheckpointer().metadata(os.path.abspath(path))
        # newer orbax wraps the tree in CheckpointMetadata.item_metadata;
        # older releases return the tree directly
        meta = getattr(meta, "item_metadata", meta)
        tpl = {
            "model": {k: sds(t.data)
                      for k, t in self.get_states().items()},
            "opt": {}, "res": {},
            "rng": meta_leaf(meta["rng"]),
        }
        if self._optimizer is not None and meta.get("opt"):
            self._optimizer.setup(self.get_params().values())
            tpl["opt"] = {f"s{i}": sds(a) for i, a in
                          enumerate(self._optimizer.state_arrays())}
        tpl["res"] = {k: meta_leaf(m)
                      for k, m in (meta.get("res") or {}).items()}
        return tpl

    def load_checkpoint(self, path: str, validate: bool = True):
        """Restore a `save_checkpoint` directory (a .../step_N path) into
        this model + its optimizer + the device RNG. The model must be
        built/compiled first so params exist, but NOT to the same
        topology: the restore template carries the LIVE training state's
        shardings, so orbax reshards the saved arrays onto whatever mesh
        this process runs — a checkpoint saved on an 8-device mesh
        restores onto 4 (or onto a single device) with the training
        state intact (tests/test_resilience.py::
        test_kill_and_resume_onto_smaller_mesh). Under `jax.distributed`
        every process calls this with the same path and receives only
        its own shards — no host ever gathers the full arrays.

        With `validate` (default) and a `step_N.manifest.json` sibling
        present (the resilience layer writes one per durable save), the
        manifest's parameter signature is checked against this model
        first — a shape/dtype mismatch raises ValueError naming the
        offending params instead of orbax failing midway through a
        partial restore; topology differences are allowed (that is the
        resharding path) and reported as a `resilience` event.
        Optimizer state (including sparse error-feedback residuals saved
        before/after their order existed) resumes exactly; bit-identical
        continuation is asserted single-process by tests/test_model.py::
        test_checkpoint_resume_equivalence and across 2 processes by
        examples/multihost/ckpt_2proc.py (the CI leg)."""
        import jax
        import orbax.checkpoint as ocp
        from . import overlap, resilience
        # barrier: an async save of THIS path (or any other) must be
        # durable before restore reads it — and its deferred error must
        # surface here rather than restore racing a half-written dir
        overlap.wait_for_checkpoints()
        manifest = resilience.read_manifest(path)
        if validate and manifest is not None:
            problems = resilience.validate_manifest(manifest, self)
            if problems:
                raise ValueError(
                    f"checkpoint {path} does not fit this model: "
                    + "; ".join(problems))
            saved = (manifest.get("mesh") or {}).get("n_devices")
            live = len(jax.devices())
            if saved and saved != live:
                observe.get_registry().emit(
                    {"kind": "resilience", "event": "reshard_restore",
                     "path": path, "saved_devices": saved,
                     "live_devices": live})
        ck = ocp.StandardCheckpointer()
        with observe.span("checkpoint.load"):
            tree = ck.restore(os.path.abspath(path),
                              self._restore_template(path))
        # direct buffer assignment: the restored arrays already carry the
        # live shardings (template), so no host round-trip — required on
        # multi-host, where np.asarray of a global array would throw
        states = self.get_states()
        for k, v in tree["model"].items():
            states[k].data = v
        if self._optimizer is not None and tree.get("opt"):
            # (setup already ran while building the restore template, so
            # the positional slot order below cannot misalign)
            opt_tree = tree["opt"]
            arrs = [opt_tree[f"s{i}"] for i in range(len(opt_tree))]
            self._optimizer.load_state_arrays(arrs)
            load_stacks = getattr(self._optimizer,
                                  "load_residual_device_stacks", None)
            if load_stacks is not None and tree.get("res"):
                load_stacks({int(k[1:]): np.asarray(v)
                             for k, v in tree["res"].items()})
        from .device import get_default_device
        dev = self._device or get_default_device()
        dev.rng_state = jax.random.wrap_key_data(
            jnp.asarray(np.asarray(tree["rng"]), jnp.uint32))
        self._compiled_step = None  # drop stale executable state binding
        return self

    def load_states(self, fpath: str) -> dict:
        # span -> the goodput `checkpoint` bucket; covers set_states too
        # (the host->device transfer is part of the restore, as on the
        # orbax path)
        with observe.span("checkpoint.load"):
            with zipfile.ZipFile(fpath, "r") as zf:
                with zf.open("tensor_dict.npz") as f:
                    loaded = dict(np.load(io.BytesIO(f.read())))
            aux = {k[len("aux."):]: v for k, v in loaded.items()
                   if k.startswith("aux.")}
            model_states = {k: v for k, v in loaded.items()
                            if not k.startswith("aux.")}
            self.set_states(model_states)
            self._compiled_step = None  # drop stale executable binding
        return aux
