import time, numpy as np, jax, jax.numpy as jnp
from singa_tpu.ops.attention import flash_attention
B,H,S,D = 8,16,1024,128
rng = np.random.RandomState(0)
q = jnp.asarray(rng.standard_normal((B,H,S,D)), jnp.bfloat16)
k = jnp.asarray(rng.standard_normal((B,H,S,D)), jnp.bfloat16)
v = jnp.asarray(rng.standard_normal((B,H,S,D)), jnp.bfloat16)
def timed(f, *a, iters=20):
    np.asarray(jax.device_get(f(*a)))
    ts = []
    for _ in range(3):
        t0 = time.perf_counter()
        o = None
        for _ in range(iters):
            o = f(*a)
        np.asarray(jax.device_get(jnp.sum(o.astype(jnp.float32))))
        ts.append((time.perf_counter()-t0)/iters*1e3)
    return min(ts)
for bq, bk in ((None,None),(1024,1024),(512,1024),(256,None),(None,None)):
    fwd = jax.jit(lambda q,k,v,bq=bq,bk=bk: flash_attention(q,k,v,True,block_q=bq,block_k=bk))
    print(f"bq={bq} bk={bk}: fwd {timed(fwd,q,k,v):.3f} ms")
