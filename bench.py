"""Driver benchmark: training throughput on synthetic data, self-validating.

Mirrors the reference harness (examples/cifar_distributed_cnn/benchmark.py:
34-92): synthetic data, time `iters` graph-mode train steps after warmup,
report throughput. Prints ONE JSON line whose headline is
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
plus self-validation fields so the number can be *believed*:
  - flops_per_step: XLA cost analysis of the exact compiled step
  - step_ms_{median,mean,p10,p90}: per-step latency distribution, each step
    fenced by a device->host fetch (immune to broken async block paths)
  - model_tflops / mfu_vs_peak: achieved FLOP rate vs the chip's bf16 peak
  - mfu_suspect: true if the pipelined reading implies >100% MFU; in that
    case the headline value falls back to the fenced per-step reading.

Models: resnet50 (img/s, MXU conv path) and gpt (tokens/s, flash-attention
path).
"""

import argparse
import json
import sys
import time


# Per-generation peaks (public spec sheets) live in singa_tpu.introspect —
# one table feeds this harness, the MFU gauge, and the explain report.
# >100% of the flops peak is a broken harness by definition, whatever the
# dtype; the HBM table drives the roofline readout (bound = memory when
# bytes/BW exceeds flops/peak).
from singa_tpu.introspect import (  # noqa: E402
    PEAK_TFLOPS_BF16 as _PEAK_TFLOPS,
    PEAK_HBM_GBS as _PEAK_HBM_GBS,
    chip_peak as _chip_peak,
)


def _chip_peak_tflops(device_kind: str):
    return _chip_peak(device_kind, _PEAK_TFLOPS)


def build_bench_model(model="resnet50", batch=32, size=224, dtype="float32",
                      gpt_dim=2048, gpt_layers=8, gpt_heads=16,
                      gpt_vocab=8192, dev=None, seed=0):
    """Build one bench model plus a synthetic batch on `dev`.

    Shared by the timed harness below and `python -m singa_tpu.introspect`
    (the explain report describes the exact executables the bench times).
    Returns (model, tx, ty, items_per_step, unit, model_factory).
    """
    import numpy as np
    from singa_tpu import device, models, tensor

    dev = dev or device.best_device()
    rng = np.random.RandomState(seed)
    if model == "gpt":
        seq = size if size > 32 else 512
        def model_factory():
            return models.create_model(
                "gpt", vocab_size=gpt_vocab, max_seq=seq, dim=gpt_dim,
                num_heads=gpt_heads, num_layers=gpt_layers)

        m = model_factory()
        ids = rng.randint(0, gpt_vocab, (batch, seq)).astype(np.int32)
        tgt = np.roll(ids, -1, axis=1).astype(np.int32)
        tx = tensor.from_numpy(ids, device=dev)
        ty = tensor.from_numpy(tgt, device=dev)
        return m, tx, ty, batch * seq, "tokens/s", model_factory
    if model == "mlp":
        def model_factory():
            return models.create_model("mlp", data_size=size,
                                       num_classes=10)

        m = model_factory()
        x_np = rng.standard_normal((batch, size)).astype(np.float32)
        y_np = rng.randint(0, 10, batch).astype(np.int32)
        tx = tensor.Tensor(data=x_np, device=dev, dtype=dtype)
        ty = tensor.from_numpy(y_np, device=dev)
        return m, tx, ty, batch, "img/s", model_factory

    def model_factory():
        return models.create_model(model, num_channels=3)

    m = model_factory()
    x_np = rng.standard_normal((batch, 3, size, size)).astype(np.float32)
    y_np = rng.randint(0, 10, batch).astype(np.int32)
    tx = tensor.Tensor(data=x_np, device=dev, dtype=dtype)
    ty = tensor.from_numpy(y_np, device=dev)
    return m, tx, ty, batch, "img/s", model_factory


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="resnet50",
                   choices=["resnet50", "resnet18", "cnn", "gpt"])
    p.add_argument("--batch", type=int, default=None,
                   help="default: 32 (resnet/cnn), 8 (gpt)")
    p.add_argument("--size", type=int, default=None,
                   help="image side (resnet) / sequence length (gpt); "
                        "default: 224 (resnet/cnn), 1024 (gpt)")
    p.add_argument("--iters", type=int, default=100)
    p.add_argument("--warmup", type=int, default=5)
    p.add_argument("--step-samples", type=int, default=30,
                   help="steps to time individually for the latency "
                        "distribution")
    p.add_argument("--dtype", default="float32",
                   choices=["float32", "bfloat16"])
    p.add_argument("--gpt-dim", type=int, default=2048,
                   help="gpt model width. The default (2048, 8 layers, "
                        "b8 s1024) is the compute-bound regime: ~62%% MFU "
                        "on v5e. Small widths (512) are memory-bound and "
                        "show ~31%% — that's the model's arithmetic "
                        "intensity, not the framework (PROFILE.md)")
    p.add_argument("--gpt-layers", type=int, default=8)
    p.add_argument("--gpt-heads", type=int, default=16)
    p.add_argument("--amp", action="store_true", default=None,
                   help="mixed precision: bf16 compute, fp32 master "
                        "weights (compile(amp='bfloat16')). Default: on "
                        "(the canonical TPU training mode); --no-amp for "
                        "pure fp32")
    p.add_argument("--no-amp", dest="amp", action="store_false")
    p.add_argument("--trace", default=None, metavar="DIR",
                   help="capture an xplane trace of the timed loop into DIR "
                        "and print a per-op device-time table (singa_tpu."
                        "xprof) to stderr — the TPU analog of the "
                        "reference's scheduler per-op profile")
    p.add_argument("--health", action="store_true",
                   help="after the main run, re-time the loop with the "
                        "training-health layer (singa_tpu.health) attached "
                        "and record the in-graph stats' per-step overhead "
                        "vs the no-health run into the JSON "
                        "(health_ms_per_step / health_overhead_pct), so "
                        "regressions in the stats cost show in BENCH_*.json")
    p.add_argument("--mem", action="store_true",
                   help="after the main run, install the device-memory "
                        "ledger (singa_tpu.memory) and A/B the fenced "
                        "step time with per-step snapshots on vs off "
                        "(paired, alternating order — same protocol as "
                        "--health), then record the overhead "
                        "(mem_ms_per_step / mem_overhead_pct), the "
                        "region breakdown, the reconciliation check, "
                        "the compile-count delta (must be 0: snapshots "
                        "are host-side only) and the pre-flight fit "
                        "estimate into the JSON record")
    p.add_argument("--watchdog", action="store_true",
                   help="measure the watchdog guard's per-step cost "
                        "(paired alternating enabled/disabled samples, "
                        "same protocol as --mem) and record "
                        "watchdog_ms_per_step / watchdog_overhead_pct "
                        "/ watchdog_compile_delta; target <=1% with "
                        "compile_count unchanged")
    p.add_argument("--regress", action="store_true",
                   help="measure the regression detector's per-step "
                        "cost (singa_tpu.regress): paired alternating "
                        "listener-attached/detached samples plus a "
                        "direct measurement of the span-listener feed "
                        "(same protocol as --watchdog) and record "
                        "regress_us_per_step / regress_overhead_pct / "
                        "regress_compile_delta; target <=1% with "
                        "compile_count unchanged")
    p.add_argument("--mem-out", default=None, metavar="FILE",
                   help="with --mem: also write the focused memory "
                        "records as JSONL (the MEM_r*.json artifact "
                        "tools/bench_trend.py aggregates)")
    p.add_argument("--explain", action="store_true",
                   help="add the AOT introspection fields to the JSON "
                        "record (singa_tpu.introspect): mfu_pct, "
                        "compile_{trace,lower,backend}_s phase times and "
                        "hbm_temps_bytes of the compiled step — mirrored "
                        "into singa_bench_* gauges like every other "
                        "field")
    p.add_argument("--goodput", action="store_true",
                   help="install the goodput tracker (singa_tpu.goodput) "
                        "for the whole run and emit the wall-time bucket "
                        "breakdown (goodput_<bucket>_s) + goodput_ratio "
                        "into the JSON record and the singa_bench_* "
                        "mirror")
    p.add_argument("--overlap", action="store_true",
                   help="A/B the overlap layer (singa_tpu.overlap): time "
                        "a fit over a sleep-injected iterator with device "
                        "prefetch off vs on and emit dispatch_us_per_step "
                        "(un-fenced call wall time — host dispatch cost "
                        "on an async backend), the goodput data_wait/step "
                        "bucket deltas per arm, and overlap_speedup into "
                        "the JSON record + singa_bench_* mirror")
    p.add_argument("--ckpt-async", action="store_true",
                   help="time save_checkpoint: async blocking portion "
                        "(ckpt_blocking_s, the device->host snapshot) vs "
                        "time to durable (ckpt_total_s, includes the "
                        "wait_for_checkpoints barrier) vs the fully "
                        "synchronous write (ckpt_sync_s)")
    p.add_argument("--resume", action="store_true",
                   help="resilience cold-vs-resumed A/B: a controller "
                        "run with periodic async saves is killed "
                        "mid-epoch (injected fault), then auto-resumed "
                        "in a fresh model from the latest valid "
                        "checkpoint; records resume_restore_s, "
                        "steps_replayed and the goodput "
                        "checkpoint-bucket delta of each arm into the "
                        "JSON record + singa_bench_* mirror")
    p.add_argument("--diag-port", type=int, default=None, metavar="PORT",
                   help="serve the live diagnostics HTTP endpoints "
                        "(/metrics /healthz /statusz /flightz /profilez) "
                        "on PORT (0 = ephemeral) while the bench runs; "
                        "implies --goodput")
    p.add_argument("--fleet-dir", default=None, metavar="DIR",
                   help="publish this process's telemetry shard "
                        "(metrics + goodput + spans) into DIR while the "
                        "bench runs, so a fleet coordinator aggregating "
                        "DIR sees the bench as one more worker "
                        "(singa_tpu.fleet)")
    p.add_argument("--metrics-out", default=None, metavar="FILE",
                   help="write the observe registry as Prometheus text "
                        "after the run (step histograms, compile counts, "
                        "and the bench numbers as singa_bench_* gauges)")
    p.add_argument("--events-out", default=None, metavar="FILE",
                   help="attach a JSONL EventLog: per-step records during "
                        "the run plus the final bench record, same schema "
                        "as runtime telemetry")
    p.add_argument("--compile-cache", default=None, metavar="DIR",
                   help="enable the warm store (singa_tpu.warmstart) "
                        "rooted at DIR: staged builds persist serialized "
                        "executables + the XLA compile cache there and a "
                        "second run loads them — with --goodput the "
                        "compile bucket collapses on the warm run; the "
                        "record gains a compile_cache section")
    args = p.parse_args()
    if args.amp is None:
        args.amp = True
    # per-model defaults; the resnet50 headline metric name
    # (resnet50_train_throughput_b32_s224_...) is pinned across rounds
    if args.batch is None:
        args.batch = 8 if args.model == "gpt" else 32
    if args.size is None:
        args.size = 1024 if args.model == "gpt" else 224

    import numpy as np
    import jax
    from singa_tpu import device, models, observe, opt, tensor

    if args.events_out:
        observe.set_event_log(args.events_out)

    if args.compile_cache:
        from singa_tpu import warmstart
        # enabled before any staged build so the FIRST compile already
        # exports into the store (and a warm rerun loads from it)
        warmstart.enable(args.compile_cache)

    goodput_tracker = None
    if args.goodput or args.diag_port is not None:
        from singa_tpu import goodput as goodput_mod
        # installed before the model exists so warmup compiles land in
        # the `compile` bucket
        goodput_tracker = goodput_mod.install()

    fleet_writer = None
    if args.fleet_dir:
        from singa_tpu import fleet
        # started before the build so compile-era spans ride the shards
        fleet_writer = fleet.start_shard_writer(args.fleet_dir,
                                                interval_s=0.5)

    dev = device.best_device()
    on_cpu = dev.is_host()
    if on_cpu:
        # host-only run (no TPU attached): shrink so the bench still finishes
        args.size = min(args.size, 64 if args.model != "gpt" else 128)
        args.iters = min(args.iters, 10)
        args.warmup = min(args.warmup, 2)
        args.step_samples = min(args.step_samples, 5)

    seq = args.size if args.size > 32 else 512  # gpt: attn-flops formula
    m, tx, ty, items_per_step, unit, model_factory = build_bench_model(
        model=args.model, batch=args.batch, size=args.size,
        dtype=args.dtype, gpt_dim=args.gpt_dim, gpt_layers=args.gpt_layers,
        gpt_heads=args.gpt_heads, dev=dev)

    sgd = opt.SGD(lr=0.1, momentum=0.9, weight_decay=1e-5)
    m.set_optimizer(sgd)
    m.compile([tx], is_train=True, use_graph=True,
              amp="bfloat16" if args.amp else None)

    if args.diag_port is not None:
        srv = observe.start_diag_server(port=args.diag_port, model=m,
                                        device=dev)
        print(f"# diag server: {srv.url} "
              "(/metrics /healthz /statusz /flightz /profilez)",
              file=sys.stderr)

    # Always run >=1 untimed step: compiles the graph and guarantees
    # out/loss exist for the fence below even with --warmup 0.
    for _ in range(max(args.warmup, 1)):
        out, loss = m(tx, ty)
    float(np.asarray(jax.device_get(loss.data)))  # hard fence: fetch to host

    # ---- pipelined throughput (reference harness semantics) --------------
    if args.trace:
        dev.StartTrace(args.trace)
    t0 = time.perf_counter()
    for _ in range(args.iters):
        out, loss = m(tx, ty)
    # Fence via device->host fetch of the final loss: it depends on the
    # whole step chain and cannot complete before the compute does, even if
    # a backend's block_until_ready is a no-op.
    final_loss = float(np.asarray(jax.device_get(loss.data)))
    elapsed = time.perf_counter() - t0
    throughput_pipelined = args.iters * items_per_step / elapsed
    if args.trace:
        dev.StopTrace()
        from singa_tpu import xprof
        rows = xprof.op_table(args.trace)
        print(f"# per-op device time over {args.iters} steps "
              f"({args.trace}):", file=sys.stderr)
        print(xprof.format_table(rows, top=30), file=sys.stderr)
        print("# by XLA hlo_category (measured time + raw bytes + flops, "
              "per step):", file=sys.stderr)
        print(xprof.format_hlo_categories(
            xprof.hlo_category_table(args.trace, steps=args.iters)),
            file=sys.stderr)

    # ---- fenced per-call latency distribution ----------------------------
    # Each call fenced by a host fetch: this bounds true step latency from
    # above (includes the host<->device round-trip, which on a tunneled
    # chip can dominate) and proves steps actually execute.
    step_ms = []
    for _ in range(args.step_samples):
        t1 = time.perf_counter()
        out, loss = m(tx, ty)
        np.asarray(jax.device_get(loss.data))
        step_ms.append((time.perf_counter() - t1) * 1e3)
    step_ms_arr = np.asarray(step_ms)
    med_ms = float(np.median(step_ms_arr))
    throughput_stepwise = items_per_step / (med_ms / 1e3)

    # ---- health-stat overhead (--health) ---------------------------------
    # A second, identically-shaped model with the in-graph numerics
    # telemetry compiled into its step (warn policy, so nothing skips).
    # The two executables are sampled as adjacent-in-time PAIRS with the
    # in-pair order alternating, and the overhead is the median of the
    # paired deltas over the median base — pairing cancels the slow load
    # drift of a shared host that makes block-wise or single-loop
    # comparisons swing by >10% run to run. The delta is the cost of the
    # fused grad-norm/isfinite/update-norm reductions plus the per-step
    # stats fetch.
    # --explain must describe the executable the timed run above used;
    # snapshot it NOW, before the --health arm compiles a second,
    # health-instrumented step under the same "step" introspect key
    explain_build = None
    if args.explain:
        from singa_tpu import introspect
        explain_build = introspect.last_build("step") or {}

    health_ms_per_step = None
    health_overhead_pct = None
    if args.health:
        import tempfile

        from singa_tpu import health as health_mod
        mh = model_factory()
        mh.set_optimizer(opt.SGD(lr=0.1, momentum=0.9, weight_decay=1e-5))
        # spike watchdog off (inf threshold): early-training loss decline
        # would otherwise trip a flight-recorder dump INSIDE a timed
        # sample (file I/O in the measurement); bundles go to a temp dir,
        # never the caller's CWD
        mh.compile([tx], is_train=True, use_graph=True,
                   amp="bfloat16" if args.amp else None,
                   health=health_mod.HealthMonitor(
                       policy="warn", spike_factor=float("inf"),
                       out_dir=tempfile.mkdtemp(prefix="bench_health_")))

        def fenced_ms(mm):
            t1 = time.perf_counter()
            _o, ls = mm(tx, ty)
            np.asarray(jax.device_get(ls.data))
            return (time.perf_counter() - t1) * 1e3

        for _ in range(max(args.warmup, 1)):
            mh(tx, ty)
        fenced_ms(mh)
        fenced_ms(m)  # both arms warm
        bases, healths = [], []
        for i in range(3 * args.step_samples):
            if i % 2 == 0:
                bases.append(fenced_ms(m))
                healths.append(fenced_ms(mh))
            else:
                healths.append(fenced_ms(mh))
                bases.append(fenced_ms(m))
        deltas = np.asarray(healths) - np.asarray(bases)
        base_ms = float(np.median(np.asarray(bases)))
        health_ms_per_step = base_ms + float(np.median(deltas))
        health_overhead_pct = 100.0 * float(np.median(deltas)) / base_ms

    # ---- device-memory ledger overhead + breakdown (--mem) ---------------
    # Same paired-alternating protocol as --health: the delta is the
    # host-side cost of one jax.live_arrays() enumeration + attribution
    # per step. The compile-count delta is asserted into the record —
    # the ledger never traces, so installing it must not retrace.
    mem_fields = {}
    if args.mem:
        from singa_tpu import memory as memory_mod

        led = memory_mod.install_ledger()

        def fenced_mem_ms():
            t1 = time.perf_counter()
            _o, ls = m(tx, ty)
            np.asarray(jax.device_get(ls.data))
            return (time.perf_counter() - t1) * 1e3

        cc = observe.get_registry().get("singa_model_compile_total")
        compiles_before = sum(v for _n, _k, v in cc.samples()) if cc else 0
        fenced_mem_ms()  # both arms warm (the first snapshot builds
        fenced_mem_ms()  # the provider id sets)
        offs, ons = [], []
        for i in range(2 * args.step_samples):
            if i % 2 == 0:
                led.enabled = False
                offs.append(fenced_mem_ms())
                led.enabled = True
                ons.append(fenced_mem_ms())
            else:
                led.enabled = True
                ons.append(fenced_mem_ms())
                led.enabled = False
                offs.append(fenced_mem_ms())
        led.enabled = True
        deltas = np.asarray(ons) - np.asarray(offs)
        mem_base_ms = float(np.median(np.asarray(offs)))
        mem_ms_per_step = mem_base_ms + float(np.median(deltas))
        mem_overhead_pct = 100.0 * float(np.median(deltas)) / mem_base_ms
        cc = observe.get_registry().get("singa_model_compile_total")
        compiles_after = sum(v for _n, _k, v in cc.samples()) if cc else 0
        snap = led.snapshot()
        # reconciliation against an INDEPENDENT enumeration (snapshot
        # accumulates regions and total in one pass, so comparing
        # those two against each other would be a tautology)
        reconciled = (sum(snap["regions"].values())
                      == snap["total_bytes"]
                      == memory_mod.total_live_bytes())
        fit = memory_mod.estimate_fit(model=m, device=dev)
        mem_fields = {
            "mem_ms_per_step": round(mem_ms_per_step, 3),
            "mem_overhead_pct": round(mem_overhead_pct, 2),
            "mem_compile_delta": int(compiles_after - compiles_before),
            "mem_reconciled": bool(reconciled),
            "mem_total_bytes": snap["total_bytes"],
            "mem_live_arrays": snap["n_arrays"],
            "mem_params_bytes": snap["regions"]["params"],
            "mem_opt_state_bytes": snap["regions"]["opt_state"],
            "mem_unattributed_bytes": snap["regions"]["unattributed"],
            "mem_est_peak_bytes": fit["estimated_peak_bytes"],
            "mem_limit_bytes": fit["limit_bytes"],
        }
        if args.mem_out:
            mem_ok = bool(reconciled
                          and compiles_after == compiles_before)
            with open(args.mem_out, "w", encoding="utf-8") as f:
                for metric, value, mu in (
                        # the overhead as an ms delta, so bench_trend's
                        # direction inference (lower-is-better on ms /
                        # _bytes) judges every record correctly
                        ("mem_overhead_ms", float(np.median(deltas)),
                         "ms"),
                        ("mem_ms_per_step", mem_ms_per_step, "ms"),
                        ("mem_total_bytes", snap["total_bytes"],
                         "bytes"),
                        ("mem_params_bytes", snap["regions"]["params"],
                         "bytes"),
                        ("mem_est_peak_bytes",
                         fit["estimated_peak_bytes"], "bytes")):
                    f.write(json.dumps(
                        {"metric": metric, "value": round(float(value), 4),
                         "unit": mu, "model": args.model}) + "\n")
                f.write(json.dumps({
                    "ok": mem_ok, "reconciled": bool(reconciled),
                    "compile_delta": int(compiles_after
                                         - compiles_before),
                    "overhead_pct": round(mem_overhead_pct, 2),
                    "regions": snap["regions"],
                    "model": args.model}) + "\n")
        memory_mod.uninstall_ledger()

    # ---- watchdog guard overhead (--watchdog) -----------------------------
    # The guard adds pure host work per step: arm (deadline resolve +
    # dict insert) + disarm (dict remove + one p99 recompute). That is
    # ~10us against a >=ms step — BELOW what the paired-A/B protocol
    # can resolve on a noisy shared host (a 300ms CPU step swings more
    # per sample than the guard costs per thousand). So the headline is
    # a DIRECT measurement: the median of many timed arm/disarm cycles
    # against the measured base step, with the paired A/B delta kept as
    # a sanity field and the compile-count delta asserted (the guard is
    # host-side only and must never retrace).
    watchdog_fields = {}
    if args.watchdog:
        from singa_tpu import watchdog as watchdog_mod

        wd = watchdog_mod.install_watchdog(floor_s=600.0,
                                           poll_interval_s=0.25)

        def fenced_wd_ms():
            t1 = time.perf_counter()
            _o, ls = m(tx, ty)
            np.asarray(jax.device_get(ls.data))
            return (time.perf_counter() - t1) * 1e3

        cc = observe.get_registry().get("singa_model_compile_total")
        wd_compiles_before = sum(
            v for _n, _k, v in cc.samples()) if cc else 0
        fenced_wd_ms()  # both arms warm
        fenced_wd_ms()
        offs, ons = [], []
        for i in range(2 * args.step_samples):
            if i % 2 == 0:
                wd.enabled = False
                offs.append(fenced_wd_ms())
                wd.enabled = True
                ons.append(fenced_wd_ms())
            else:
                wd.enabled = True
                ons.append(fenced_wd_ms())
                wd.enabled = False
                offs.append(fenced_wd_ms())
        wd.enabled = True
        # direct guard cost: batches of arm/disarm cycles, median batch
        # (the step path arms exactly one `step` guard per step)
        batch_n, batches = 200, []
        for _ in range(15):
            t1 = time.perf_counter()
            for _ in range(batch_n):
                with watchdog_mod.guard("step"):
                    pass
            batches.append((time.perf_counter() - t1) / batch_n)
        guard_us = float(np.median(np.asarray(batches))) * 1e6
        deltas = np.asarray(ons) - np.asarray(offs)
        wd_base_ms = float(np.median(np.asarray(offs)))
        wd_overhead_pct = 100.0 * (guard_us / 1e3) / wd_base_ms
        cc = observe.get_registry().get("singa_model_compile_total")
        wd_compiles_after = sum(
            v for _n, _k, v in cc.samples()) if cc else 0
        step_state = wd.op_state("step")
        watchdog_fields = {
            "watchdog_guard_us": round(guard_us, 3),
            "watchdog_ms_per_step": round(wd_base_ms + guard_us / 1e3,
                                          3),
            "watchdog_overhead_pct": round(wd_overhead_pct, 4),
            "watchdog_ab_delta_pct": round(
                100.0 * float(np.median(deltas)) / wd_base_ms, 2),
            "watchdog_compile_delta": int(wd_compiles_after
                                          - wd_compiles_before),
            "watchdog_step_samples": len(step_state.samples),
            "watchdog_step_deadline_s": step_state.deadline(),
            "watchdog_ok": bool(
                wd_overhead_pct <= 1.0
                and wd_compiles_after == wd_compiles_before),
        }
        watchdog_mod.uninstall_watchdog()

    # ---- regression-detector overhead (--regress) -------------------------
    # Same story as the watchdog guard: the detector adds pure host work
    # per step — one span-listener callback (leaf split, signal map,
    # lock, deque append; every `window`th call also closes a window:
    # a sorted() median + the CUSUM update). Far below what the paired
    # A/B resolves on a noisy host, so the headline is the DIRECT
    # median of many timed feed calls against the measured base step,
    # with the paired delta as a sanity field and the compile-count
    # delta asserted (the detector is host-side only and must never
    # retrace).
    regress_fields = {}
    if args.regress:
        from singa_tpu import regress as regress_mod

        # h high enough that noisy benchmark steps never convict
        # mid-measurement (a conviction writes a bundle — not a cost
        # the steady-state number should include)
        det = regress_mod.RegressionDetector(
            warmup_samples=16, window=8, h=1e9).install()

        def fenced_rg_ms():
            t1 = time.perf_counter()
            _o, ls = m(tx, ty)
            np.asarray(jax.device_get(ls.data))
            return (time.perf_counter() - t1) * 1e3

        cc = observe.get_registry().get("singa_model_compile_total")
        rg_compiles_before = sum(
            v for _n, _k, v in cc.samples()) if cc else 0
        # idempotent toggles (add_span_listener is append-only; remove
        # drops every equal copy, so detach-then-attach never doubles)
        def rg_off():
            observe.remove_span_listener(det._on_span)

        def rg_on():
            observe.remove_span_listener(det._on_span)
            observe.add_span_listener(det._on_span)

        fenced_rg_ms()  # both arms warm
        fenced_rg_ms()
        offs, ons = [], []
        for i in range(2 * args.step_samples):
            if i % 2 == 0:
                rg_off()
                offs.append(fenced_rg_ms())
                rg_on()
                ons.append(fenced_rg_ms())
            else:
                rg_on()
                ons.append(fenced_rg_ms())
                rg_off()
                offs.append(fenced_rg_ms())
        rg_on()
        rg_base_ms = float(np.median(np.asarray(offs)))
        # direct feed cost at steady state: freeze the baseline on
        # constant samples (z stays 0, no convictions), then time
        # batches of listener calls — each 8th closes a real window
        base_s = rg_base_ms / 1e3
        for _ in range(16):
            det._on_span("model.step", base_s, {})
        batch_n, batches = 200, []
        for _ in range(15):
            t1 = time.perf_counter()
            for _ in range(batch_n):
                det._on_span("model.step", base_s, {})
            batches.append((time.perf_counter() - t1) / batch_n)
        feed_us = float(np.median(np.asarray(batches))) * 1e6
        deltas = np.asarray(ons) - np.asarray(offs)
        rg_overhead_pct = 100.0 * (feed_us / 1e3) / rg_base_ms
        cc = observe.get_registry().get("singa_model_compile_total")
        rg_compiles_after = sum(
            v for _n, _k, v in cc.samples()) if cc else 0
        rg_state = det.signal_state("model.step") or {}
        regress_fields = {
            "regress_us_per_step": round(feed_us, 3),
            "regress_ms_per_step": round(rg_base_ms + feed_us / 1e3,
                                         3),
            "regress_overhead_pct": round(rg_overhead_pct, 4),
            "regress_ab_delta_pct": round(
                100.0 * float(np.median(deltas)) / rg_base_ms, 2),
            "regress_compile_delta": int(rg_compiles_after
                                         - rg_compiles_before),
            "regress_windows": int(rg_state.get("windows") or 0),
            "regress_ok": bool(
                rg_overhead_pct <= 1.0
                and rg_compiles_after == rg_compiles_before),
        }
        regress_mod.uninstall()

    # ---- overlap layer A/B (--overlap / --ckpt-async) --------------------
    # the record's goodput_* fields must describe the REAL benchmarked
    # run: snapshot before the A/B arms feed the same tracker synthetic
    # sleep-injected stalls and extra checkpoint saves
    goodput_snap = None
    if goodput_tracker is not None and (args.overlap or args.ckpt_async
                                        or args.resume):
        goodput_snap = goodput_tracker.snapshot(final=True)
    overlap_fields = {}
    if args.overlap:
        from singa_tpu import goodput as goodput_mod
        tracker = goodput_mod.install()  # idempotent with --goodput
        # dispatch-path cost: un-fenced call wall time — on an async
        # backend the device runs behind, so this is the host-side
        # dispatch the fast path trims; fenced medians are above
        for _ in range(3):
            m(tx, ty)
        samp = []
        for _ in range(max(10, args.step_samples)):
            t1 = time.perf_counter()
            out, loss = m(tx, ty)
            samp.append(time.perf_counter() - t1)
        np.asarray(jax.device_get(loss.data))  # fence before the A/B
        pipelined_now = elapsed / args.iters
        sleep_s = min(max(pipelined_now / 3.0, 0.002), 0.05)
        n_ab = 6 if on_cpu else 12

        class _SlowSrc:  # the injected host-side stall per batch
            def __iter__(self):
                for _ in range(n_ab):
                    time.sleep(sleep_s)
                    yield (tx, ty)

        def _fit_arm(prefetch):
            s0 = tracker.snapshot()["buckets"]
            t1 = time.perf_counter()
            m.fit(_SlowSrc(), epochs=1, prefetch_to_device=prefetch)
            wall = time.perf_counter() - t1
            s1 = tracker.snapshot()["buckets"]
            return wall, {k: s1[k] - s0[k] for k in s1}

        wall_off, bk_off = _fit_arm(0)
        wall_on, bk_on = _fit_arm(2)
        overlap_fields = {
            "dispatch_us_per_step":
                round(float(np.median(np.asarray(samp))) * 1e6, 2),
            "overlap_sleep_s": round(sleep_s, 4),
            "overlap_batches": n_ab,
            "overlap_wall_off_s": round(wall_off, 4),
            "overlap_wall_on_s": round(wall_on, 4),
            "overlap_speedup": round(wall_off / wall_on, 4)
            if wall_on > 0 else None,
            "overlap_data_wait_off_s": round(bk_off["data_wait"], 4),
            "overlap_data_wait_on_s": round(bk_on["data_wait"], 4),
            "overlap_step_off_s": round(bk_off["step"], 4),
            "overlap_step_on_s": round(bk_on["step"], 4),
        }
    if args.ckpt_async:
        import shutil
        import tempfile

        from singa_tpu import overlap as overlap_mod
        ckdir = tempfile.mkdtemp(prefix="bench_ckpt_")
        try:
            if overlap_mod.async_available():
                m.save_checkpoint(ckdir, step=0)  # warm orbax's pools
                overlap_mod.wait_for_checkpoints()
                t1 = time.perf_counter()
                m.save_checkpoint(ckdir, step=1)
                blocking_s = time.perf_counter() - t1
                overlap_mod.wait_for_checkpoints()
                total_s = time.perf_counter() - t1
                overlap_fields["ckpt_blocking_s"] = round(blocking_s, 4)
                overlap_fields["ckpt_total_s"] = round(total_s, 4)
            t1 = time.perf_counter()
            m.save_checkpoint(ckdir, step=2, async_save=False)
            overlap_fields["ckpt_sync_s"] = round(
                time.perf_counter() - t1, 4)
        finally:
            shutil.rmtree(ckdir, ignore_errors=True)

    # ---- resilience cold-vs-resumed A/B (--resume) -----------------------
    if args.resume:
        import shutil
        import tempfile

        from singa_tpu import goodput as goodput_mod
        from singa_tpu import resilience as res_mod
        tracker = goodput_mod.install()  # idempotent with --goodput
        ckdir = tempfile.mkdtemp(prefix="bench_resume_")
        n_steps, save_every, kill_at = 8, 3, 7
        data = [(tx, ty)] * n_steps
        try:
            def _arm_model():
                mm = model_factory()
                mm.set_optimizer(opt.SGD(lr=0.1, momentum=0.9,
                                         weight_decay=1e-5))
                mm.compile([tx], is_train=True, use_graph=True,
                           amp="bfloat16" if args.amp else None)
                return mm

            # cold arm: fresh start under the controller, killed at
            # step `kill_at` by an injected fault — it leaves durable
            # checkpoints behind (manifest of step 3 flushed by save 6)
            res_mod.install_fault_plan(
                res_mod.FaultPlan().fail("step", step=kill_at))
            # build/compile OUTSIDE the timed region, like the warm arm
            cold_model = _arm_model()
            b0 = tracker.snapshot()["buckets"]
            t1 = time.perf_counter()
            killed = False
            try:
                res_mod.TrainController(
                    cold_model, ckdir, save_every_steps=save_every,
                    max_restarts=0, handle_signals=False).fit(data)
            except RuntimeError as e:
                # ONLY the injected kill is expected; a genuine failure
                # must not be recorded as a valid cold arm
                if "injected fault" not in str(e):
                    raise
                killed = True
            if not killed:
                raise RuntimeError(
                    f"--resume cold arm completed; the injected kill at "
                    f"step {kill_at} never fired")
            cold_wall = time.perf_counter() - t1
            res_mod.clear_fault_plan()
            from singa_tpu import overlap as overlap_mod
            overlap_mod.wait_for_checkpoints()
            b1 = tracker.snapshot()["buckets"]

            # resumed arm: fresh model, same dir — restore + replay +
            # finish the remaining steps
            ctrl = res_mod.TrainController(
                _arm_model(), ckdir, save_every_steps=save_every,
                handle_signals=False)
            t1 = time.perf_counter()
            rep = ctrl.fit(data)
            warm_wall = time.perf_counter() - t1
            b2 = tracker.snapshot()["buckets"]
            overlap_fields.update({
                "resume_steps": n_steps,
                "resume_killed_at_step": kill_at,
                # batches the resumed arm consumed without training to
                # reach its checkpoint — which is also the step it
                # resumed from (single-epoch arm), so record it once
                "resume_steps_replayed": rep["resumed_step"],
                "resume_restore_s": rep["resume_restore_s"],
                "resume_cold_wall_s": round(cold_wall, 4),
                "resume_warm_wall_s": round(warm_wall, 4),
                "resume_ckpt_cold_s": round(
                    b1["checkpoint"] - b0["checkpoint"], 4),
                "resume_ckpt_warm_s": round(
                    b2["checkpoint"] - b1["checkpoint"], 4),
                "resume_step_warm_s": round(b2["step"] - b1["step"], 4),
            })
        finally:
            res_mod.clear_fault_plan()
            shutil.rmtree(ckdir, ignore_errors=True)

    # ---- self-validation against physics ---------------------------------
    ca = m.step_cost_analysis()
    flops_per_step = float(ca.get("flops", 0.0)) if ca else 0.0
    bytes_per_step = float(ca.get("bytes accessed", 0.0)) if ca else 0.0
    # XLA's cost analysis credits custom-calls ZERO flops, so the Pallas
    # flash-attention kernels vanish from the gpt model's count. Add the
    # analytic causal-attention work (fwd 2 matmuls + bwd ~2.5x fwd,
    # halved for causal masking) so MFU reflects the executed math; the
    # uncorrected figure is kept as mfu_xla_counted.
    attn_flops = 0.0
    if args.model == "gpt" and flops_per_step:
        per_layer_fwd = 0.5 * 4 * args.batch * seq * seq * args.gpt_dim
        attn_flops = args.gpt_layers * per_layer_fwd * 3.5
    kind = getattr(dev.jax_device, "device_kind", "")
    peak = _chip_peak_tflops(kind)
    peak_bw = _chip_peak(kind, _PEAK_HBM_GBS)
    # achieved rate from the amortized pipelined loop (the fenced per-call
    # numbers include the transfer round-trip, so they underestimate MFU)
    pipelined_s_per_step = elapsed / args.iters
    model_tflops = ((flops_per_step + attn_flops) / pipelined_s_per_step
                    / 1e12 if flops_per_step else None)
    mfu = model_tflops / peak if (model_tflops and peak) else None
    mfu_xla = (flops_per_step / pipelined_s_per_step / 1e12 / peak
               if (flops_per_step and peak) else None)
    suspect = bool(mfu and mfu > 1.0)

    # Roofline readout: which wall does this step lean on?  The bytes floor
    # uses XLA's "bytes accessed" (an over-count of true HBM traffic — fused
    # intermediates never reach HBM), so an effective BW above the chip's
    # peak means fusion eliminated that much traffic, not broken physics.
    compute_floor_ms = (flops_per_step / (peak * 1e12) * 1e3
                        if (flops_per_step and peak) else None)
    hbm_floor_ms = (bytes_per_step / (peak_bw * 1e9) * 1e3
                    if (bytes_per_step and peak_bw) else None)
    bound = None
    if compute_floor_ms and hbm_floor_ms:
        bound = "memory" if hbm_floor_ms > compute_floor_ms else "compute"
    effective_bw_gbs = (bytes_per_step / pipelined_s_per_step / 1e9
                        if bytes_per_step else None)
    # "bytes accessed" over-counts true HBM traffic (fused intermediates
    # never leave VMEM); when the implied BW exceeds the chip's physical
    # peak, say so IN THE ARTIFACT rather than leaving a reader to trend
    # an impossible number (the measured raw-bytes roofline lives in the
    # --trace tables / PROFILE.md).
    bytes_metric = None
    if effective_bw_gbs and peak_bw and effective_bw_gbs > peak_bw:
        bytes_metric = "xla_overcount"

    # Headline: pipelined if physically plausible, else the fenced number.
    value = throughput_stepwise if suspect else throughput_pipelined

    # Baseline: the reference publishes no absolute numbers (BASELINE.md);
    # use any number recorded in BASELINE.json "published". With no
    # published number, 0.0 + note — never report fake parity.
    vs = 0.0
    vs_northstar = None
    vs_a100 = None
    baseline_used = None
    note = "no published reference baseline for this metric " \
           "(BASELINE.md); vs_baseline not computable"
    try:
        import os
        here = os.path.dirname(os.path.abspath(__file__))
        with open(os.path.join(here, "BASELINE.json")) as f:
            pub = json.load(f).get("published", {})
        # AMP runs compare against the CudaGPU AMP figure, fp32 runs
        # against the fp32 figure (derivation: BASELINE.md).
        key = f"{args.model}_img_per_sec" + ("" if args.amp else "_fp32")
        base = pub.get(key)
        if base:
            vs = value / float(base)
            vs_northstar = vs / 1.2   # >=1.0 => north-star (1.2x) met
            baseline_used = f"{key}={base} (V100, BASELINE.md)"
            note = None
        a100 = pub.get(f"{args.model}_img_per_sec_a100_amp")
        if a100 and args.amp:
            vs_a100 = value / float(a100)
    except Exception:
        pass
    if on_cpu:
        vs = 0.0
        vs_northstar = None
        vs_a100 = None
        note = "cpu fallback (no TPU attached): shrunk shapes, not " \
               "comparable to any accelerator baseline"

    rec = {
        "metric": f"{args.model}_train_throughput_b{args.batch}_s{args.size}"
                  f"_{args.dtype}" + ("_amp_bf16" if args.amp else "")
                  + ("_cpu" if on_cpu else ""),
        "value": round(value, 2),
        "unit": unit,
        "vs_baseline": round(vs, 3),
        "vs_northstar_1_2x": round(vs_northstar, 3)
        if vs_northstar is not None else None,
        "vs_a100_amp": round(vs_a100, 3) if vs_a100 is not None else None,
        "baseline_used": baseline_used,
        "throughput_pipelined": round(throughput_pipelined, 2),
        "throughput_stepwise_fenced": round(throughput_stepwise, 2),
        "roundtrip_ms_median": round(med_ms, 3),
        "roundtrip_ms_p10": round(float(np.percentile(step_ms_arr, 10)), 3),
        "roundtrip_ms_p90": round(float(np.percentile(step_ms_arr, 90)), 3),
        "pipelined_ms_per_step": round(pipelined_s_per_step * 1e3, 3),
        "flops_per_step": flops_per_step,
        "bytes_per_step": bytes_per_step,
        "device_kind": kind or "unknown",
        "peak_tflops_bf16": peak,
        "peak_hbm_gbs": peak_bw,
        "model_tflops": round(model_tflops, 3) if model_tflops else None,
        "mfu_vs_peak": round(mfu, 4) if mfu else None,
        "attn_flops_per_step": attn_flops or None,
        "mfu_xla_counted": round(mfu_xla, 4)
        if (mfu_xla is not None and attn_flops) else None,
        "mfu_suspect": suspect,
        "health_ms_per_step": round(health_ms_per_step, 3)
        if health_ms_per_step is not None else None,
        "health_overhead_pct": round(health_overhead_pct, 2)
        if health_overhead_pct is not None else None,
        "compute_floor_ms": round(compute_floor_ms, 3)
        if compute_floor_ms else None,
        "hbm_floor_ms": round(hbm_floor_ms, 3) if hbm_floor_ms else None,
        "roofline_bound": bound,
        "effective_bw_gbs": round(effective_bw_gbs, 1)
        if effective_bw_gbs else None,
        "bytes_metric": bytes_metric,
        "final_loss": final_loss,
    }
    if note:
        rec["note"] = note
    if goodput_tracker is not None:
        # one FINAL snapshot: commits the held last step + flushes the
        # unattributed residual, so the bucket fields (and the counters
        # --metrics-out exports below) sum to the run's wall clock
        # (each lands in singa_bench_goodput_* via record_bench); a
        # pre-A/B snapshot taken above wins, so --overlap/--ckpt-async
        # arms can't skew the headline ratio
        snap = goodput_snap if goodput_snap is not None \
            else goodput_tracker.snapshot(final=True)
        rec["goodput_ratio"] = round(snap["goodput_ratio"], 4)
        rec["goodput_window_ratio"] = round(
            snap["window_goodput_ratio"], 4)
        rec["goodput_wall_s"] = round(snap["wall_s"], 3)
        for bucket_name, seconds in snap["buckets"].items():
            rec[f"goodput_{bucket_name}_s"] = round(seconds, 4)
    if mem_fields:
        rec.update(mem_fields)  # mirrored into singa_bench_* below
    if watchdog_fields:
        rec.update(watchdog_fields)  # mirrored into singa_bench_* below
    if regress_fields:
        rec.update(regress_fields)  # mirrored into singa_bench_* below
    if overlap_fields:
        rec.update(overlap_fields)  # mirrored into singa_bench_* below
    if args.compile_cache:
        from singa_tpu import warmstart
        ws = warmstart.snapshot()
        rec["compile_cache"] = {
            "root": ws["root"], "lookups": ws["lookups"],
            "hit_rate": ws["hit_rate"], "exports": ws["exports"],
            "entries": ws.get("entries"),
            "store_bytes": ws.get("store_bytes")}
        if ws["hit_rate"] is not None:
            rec["compile_cache_hit_rate"] = round(ws["hit_rate"], 4)
    if args.explain:
        # the timed step compiled through the AOT stages (model.py); use
        # the build record snapshotted before the --health arm rather
        # than re-lowering anything
        b = explain_build or {}
        ph = b.get("phases") or {}
        mem = b.get("memory") or {}
        rec.update({
            "mfu_pct": round(mfu * 100.0, 2) if mfu else None,
            "compile_trace_s": round(ph["trace"], 4)
            if "trace" in ph else None,
            "compile_lower_s": round(ph["lower"], 4)
            if "lower" in ph else None,
            "compile_backend_s": round(ph["compile"], 4)
            if "compile" in ph else None,
            "hbm_temps_bytes": mem.get("temps"),
        })
    # one schema: the BENCH_*.json record also lands in the registry
    # (singa_bench_* gauges) and the EventLog, next to the per-step
    # telemetry the run itself produced
    observe.record_bench(rec)
    if args.metrics_out:
        with open(args.metrics_out, "w") as f:
            f.write(observe.to_prometheus_text())
    if fleet_writer is not None:
        from singa_tpu import fleet
        # final publish carries the bench record's singa_bench_* gauges
        fleet.stop_shard_writer()
    print(json.dumps(rec))
    return 0


if __name__ == "__main__":
    sys.exit(main())
