"""Driver benchmark: ResNet-50 training throughput on synthetic data.

Mirrors the reference harness (examples/cifar_distributed_cnn/benchmark.py:
34-92): synthetic 224x224 batch-32 images, time `niters` graph-mode train
steps after warmup, report images/sec. Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
"""

import argparse
import json
import sys
import time


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="resnet50")
    p.add_argument("--batch", type=int, default=32)
    p.add_argument("--size", type=int, default=224)
    p.add_argument("--iters", type=int, default=100)
    p.add_argument("--warmup", type=int, default=5)
    p.add_argument("--dtype", default="float32", choices=["float32", "bfloat16"])
    args = p.parse_args()

    import numpy as np
    import jax
    from singa_tpu import device, models, opt, tensor

    dev = device.best_device()
    on_cpu = dev.is_host()
    if on_cpu:
        # host-only run (no TPU attached): shrink so the bench still finishes
        args.size = min(args.size, 64)
        args.iters = min(args.iters, 10)
        args.warmup = 2

    rng = np.random.RandomState(0)
    x_np = rng.standard_normal((args.batch, 3, args.size, args.size)).astype(
        np.float32)
    y_np = rng.randint(0, 10, args.batch).astype(np.int32)

    m = models.create_model(args.model, num_channels=3)
    sgd = opt.SGD(lr=0.1, momentum=0.9, weight_decay=1e-5)
    m.set_optimizer(sgd)
    tx = tensor.Tensor(data=x_np, device=dev, dtype=args.dtype)
    ty = tensor.from_numpy(y_np, device=dev)
    m.compile([tx], is_train=True, use_graph=True)

    for _ in range(args.warmup):
        out, loss = m(tx, ty)
    jax.block_until_ready((out.data, loss.data))
    t0 = time.perf_counter()
    for _ in range(args.iters):
        out, loss = m(tx, ty)
    # fence on the actual result buffers — Device.Sync may not block under
    # every backend's client
    jax.block_until_ready((out.data, loss.data))
    elapsed = time.perf_counter() - t0

    throughput = args.iters * args.batch / elapsed
    # Baseline: the reference publishes no absolute numbers (BASELINE.md);
    # use any number recorded in BASELINE.json "published", else 1.0.
    vs = 1.0
    try:
        with open("BASELINE.json") as f:
            pub = json.load(f).get("published", {})
        base = pub.get("resnet50_img_per_sec")
        if base:
            vs = throughput / float(base)
    except Exception:
        pass

    print(json.dumps({
        "metric": f"{args.model}_train_throughput_b{args.batch}_s{args.size}"
                  + ("_cpu" if on_cpu else ""),
        "value": round(throughput, 2),
        "unit": "img/s",
        "vs_baseline": round(vs, 3),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
